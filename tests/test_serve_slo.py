"""Serving SLO plane (ISSUE 14): per-request tracing, the latency
decomposition + slot-time ledger, serve node-series integration, the
SLO verdict engine and the verdict-driven scale policy.

Tier-1 core: the count-bucket resolution guard (satellite), request
trace-id propagation through every lifecycle edge, lease-expiry
requeue accounting under the conservation pin (satellite), the
live-vs-forensic `tpurun requests` agreement gate (satellite), the
SLO engine's multi-window burn-rate confirmation + listener contract,
the scale policy's cooldown/auto-scaler feed, serve `{node=}` gauges
and the `serve`-flavored straggler verdict, the mttr/goodput
`serving_scale` derivation — and the acceptance wedges: (A) a real
router + two serve workers over RPC with one injected-slow worker →
serve gauges on the master registry, a SERVE_SLO_VIOLATION with
burn-rate evidence under one trace id, the auto-scaler acting on the
proposal through the live-resize path, and the slot-seconds ledger
summing to slots × wall within 1%; (B) a subprocess serve worker so
one request's lifecycle spans ≥2 pids in the merged Perfetto view."""

import json
import os
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common import comm
from dlrover_tpu.common.config import get_context
from dlrover_tpu.master.local_master import start_local_master
from dlrover_tpu.master.monitor.node_series import NodeRuntimeStore
from dlrover_tpu.master.monitor.serve_slo import (
    ServeSLOEngine,
    ServingScalePolicy,
)
from dlrover_tpu.master.monitor.straggler import StragglerDetector
from dlrover_tpu.models import llama
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.serving.engine import ServeEngine, ServeExecutor
from dlrover_tpu.serving.router import RequestRouter
from dlrover_tpu.serving.slo import ServeRuntimeReportHook
from dlrover_tpu.telemetry import (
    EventKind,
    names as tm,
    read_events,
    recent_events,
)
from dlrover_tpu.telemetry.events import clear_ring
from dlrover_tpu.telemetry.goodput import derive_goodput, derive_slot_ledger
from dlrover_tpu.telemetry.metrics import (
    COUNT_BUCKETS,
    DURATION_BUCKETS,
    LATENCY_BUCKETS,
    MetricsRegistry,
    process_registry,
)
from dlrover_tpu.telemetry.mttr import derive_incidents

BOUNDS = [0.001, 0.005, 0.01, 0.05, 0.1, 1.0]
TINY = llama.llama_tiny()


@pytest.fixture(autouse=True)
def _telemetry_on():
    ctx = get_context()
    prev = ctx.telemetry_enabled
    ctx.telemetry_enabled = True
    yield
    ctx.telemetry_enabled = prev


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init(jax.random.PRNGKey(0), TINY)


@pytest.fixture(scope="module")
def engine(tiny_params):
    eng = ServeEngine(
        TINY, strategy=Strategy(mesh=MeshPlan(data=-1),
                                rule_set="llama"),
        serve_slots=4, prefill_chunk=8, max_seq=48, page_size=8,
    )
    eng.prepare(tiny_params)
    return eng


def _prompt(n=6, seed=0):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(0, TINY.vocab_size, size=(n,))]


def _serve_node_report(node, steps_total, counts, tokens=0.0,
                       occupancy=0.0, queue_len=0.0, slots=4.0):
    return comm.NodeRuntimeReport(
        node_id=node, node_type="serve", timestamp=time.time(),
        step=int(steps_total), steps_total=float(steps_total),
        bounds=BOUNDS, step_time_counts=list(counts),
        serve_tokens_total=float(tokens),
        serve_slot_occupancy=float(occupancy),
        serve_queue_len=float(queue_len), serve_slots=float(slots),
        rss_mb=1.0,
    )


def _counts_at(ms_per_step, steps):
    import bisect

    counts = [0] * (len(BOUNDS) + 1)
    idx = bisect.bisect_left(BOUNDS, ms_per_step / 1000.0)
    counts[min(idx, len(BOUNDS))] += steps
    return counts


# -- satellite: the bucket-resolution trap ------------------------------------


class TestBucketResolution:
    def test_count_histogram_with_duration_buckets_is_refused(self):
        """The trap SERVE_TOKENS_PER_REQUEST fell into: a count-valued
        histogram silently created on the 0.5ms–60s duration buckets
        lands every real request in the overflow tail. The registry
        now catches it at creation."""
        reg = MetricsRegistry()
        with pytest.raises(ValueError, match="DURATION_BUCKETS"):
            reg.histogram("dlrover_test_tokens_per_request")
        with pytest.raises(ValueError, match="DURATION_BUCKETS"):
            reg.histogram("dlrover_test_items",
                          buckets=DURATION_BUCKETS)
        # durations and explicit count buckets both pass
        reg.histogram("dlrover_test_wait_seconds")
        reg.histogram("dlrover_test_tokens_per_request",
                      buckets=COUNT_BUCKETS)

    def test_tokens_per_request_percentiles_are_count_scale(self):
        process_registry().reset()
        r = RequestRouter(lease_timeout_secs=0)
        for n in (3, 5, 9):
            rid = r.submit([1, 2], 16)
            r.lease(0, 1)
            r.complete(0, rid, list(range(n)))
        h = process_registry().get(tm.SERVE_TOKENS_PER_REQUEST)
        assert tuple(h.bounds) == tuple(float(b) for b in COUNT_BUCKETS)
        p50 = h.percentile(0.50)
        # on DURATION_BUCKETS every observation clamped at the 60s
        # bound; on count buckets the median sits in the 4..8 range
        assert p50 is not None and p50 <= 8.0

    def test_serve_latency_histograms_resolve_sub_ms(self):
        """The audit of the other SERVE_* histograms: decode-step,
        TTFT/TPOT/queue-wait/e2e/prefill are ms-scale latencies and
        use LATENCY_BUCKETS (finest bound 50µs), not the seconds-scale
        defaults."""
        process_registry().reset()
        r = RequestRouter(lease_timeout_secs=0)
        rid = r.submit([1], 4)
        r.lease(0, 1)
        r.complete(0, rid, [1, 2], ttft_s=0.0002, e2e_s=0.0006)
        for name in (tm.SERVE_TTFT_TIME, tm.SERVE_E2E_TIME,
                     tm.SERVE_QUEUE_WAIT_TIME, tm.SERVE_TPOT_TIME):
            h = process_registry().get(name)
            assert h is not None, name
            assert h.bounds[0] == pytest.approx(
                LATENCY_BUCKETS[0]), name
        # a 200µs TTFT is below DURATION_BUCKETS' first bound but
        # resolves here
        assert process_registry().get(
            tm.SERVE_TTFT_TIME).percentile(0.5) < 0.0005


# -- per-request tracing + latency decomposition ------------------------------


class TestRequestTracing:
    def test_one_trace_id_rides_every_lifecycle_edge(self):
        clear_ring()
        process_registry().reset()
        r = RequestRouter(lease_timeout_secs=0.01)
        rid = r.submit(_prompt(4), 8)
        leased = r.lease(0, 1)
        tid = leased[0]["trace_id"]
        assert tid.startswith("req-")
        time.sleep(0.05)
        assert r.scan_expired_once() == [rid]
        again = r.lease(1, 1)
        assert again[0]["trace_id"] == tid  # survives the re-lease
        r.complete(1, rid, [5, 6], ttft_s=0.01, e2e_s=0.03)
        chain = [e["kind"] for e in recent_events()
                 if e.get("trace_id") == tid]
        assert chain == [
            EventKind.SERVE_REQUEST_SUBMITTED,
            EventKind.SERVE_REQUEST_LEASED,
            EventKind.SERVE_LEASE_EXPIRED,
            EventKind.SERVE_REQUEST_LEASED,
            EventKind.SERVE_REQUEST_COMPLETED,
        ]

    def test_report_carries_the_latency_decomposition(self):
        process_registry().reset()
        r = RequestRouter(lease_timeout_secs=0)
        rid = r.submit([1, 2, 3], 8)
        r.lease(0, 1)
        r.complete(0, rid, [1, 2, 3, 4, 5], ttft_s=0.02, e2e_s=0.10)
        lat = r.report()["latency"]
        assert lat["queue_wait_p50_s"] is not None
        # tpot = (0.10 - 0.02) / 4 = 0.02, inside its bucket's range
        assert lat["tpot_p50_s"] == pytest.approx(0.02, rel=0.5)
        assert set(lat) >= {"ttft_p95_s", "e2e_p95_s",
                            "queue_wait_p95_s", "tpot_p95_s"}


# -- satellite: lease-expiry requeue accounting -------------------------------


class TestLeaseExpiryRequeueAccounting:
    def test_expired_mid_decode_counts_once_under_one_trace_id(self):
        """A request that expires mid-decode and re-leases to a second
        worker: ONE submitted, ONE completed, both lease spans under
        one request trace id, tokens never double-credited."""
        clear_ring()
        process_registry().reset()
        r = RequestRouter(lease_timeout_secs=0.01)
        rid = r.submit(_prompt(4), 8)
        assert r.lease(0, 1)  # worker 0 starts decoding
        time.sleep(0.05)
        r.scan_expired_once()  # worker 0 went silent mid-decode
        assert r.lease(1, 1)[0]["request_id"] == rid  # worker 1 takes it
        # worker 1 finishes; worker 0's late twin completion is a no-op
        assert r.complete(1, rid, [7, 8, 9], ttft_s=0.01, e2e_s=0.02)
        assert not r.complete(0, rid, [7, 8, 9])
        rep = r.report()
        req = rep["requests"]
        assert req["submitted"] == 1 and req["completed"] == 1
        assert req["dropped"] == 0 and req["leases_expired"] == 1
        # tokens credited once, to the COMPLETING node only
        assert rep["nodes"]["1"]["tokens"] == 3
        assert rep["nodes"].get("0", {}).get("tokens", 0) == 0
        leases = [e for e in recent_events()
                  if e["kind"] == EventKind.SERVE_REQUEST_LEASED]
        assert len(leases) == 2
        assert leases[0]["trace_id"] == leases[1]["trace_id"]
        assert {e["lease_node"] for e in leases} == {0, 1}
        # the tokens histogram observed exactly one request
        assert process_registry().get(
            tm.SERVE_TOKENS_PER_REQUEST).count == 1


# -- satellite: live-vs-forensic agreement ------------------------------------


class TestRequestsCliAgreement:
    def test_live_and_forensic_counts_agree_after_chaos(
            self, tmp_path, monkeypatch):
        """The `tpurun data` gate pattern: the CLI's --events
        aggregation and the live get_serve_report() RPC must agree on
        submitted/completed/evicted/expired after a chaos run (an
        expiry + a late twin completion)."""
        events_path = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", events_path)
        process_registry().reset()
        from dlrover_tpu.master.servicer import MasterServicer
        from dlrover_tpu.serving.cli import _forensic_report

        sv = MasterServicer()
        sv.request_router._timeout = 0.01
        rids = []
        for i in range(3):
            resp = sv.report(comm.ServeSubmit(
                prompt=_prompt(4, seed=i), max_new_tokens=4))
            rids.append(resp.data)
        sv.get(comm.ServeLeaseRequest(node_id=0, max_requests=2))
        time.sleep(0.05)
        sv.request_router.scan_expired_once()  # both leases expire
        sv.get(comm.ServeLeaseRequest(node_id=1, max_requests=3))
        from dlrover_tpu.telemetry.events import emit_event

        for i, rid in enumerate(rids):
            # the re-leased worker's pool hits on the later two (the
            # first cold-published); its admit path emits the HIT edge
            # the forensic prefix columns count
            hit = 8 if i > 0 else 0
            if hit:
                emit_event(EventKind.SERVE_PREFIX_HIT,
                           request_id=rid, hit_tokens=hit,
                           prompt_tokens=12)
            sv.report(comm.ServeResult(
                node_id=1, request_id=rid, tokens=[1, 2],
                ttft_s=0.01, e2e_s=0.02, prefix_hit_tokens=hit))
        # the stale twin double-completes one — must not count twice
        # (nor double its prefix-hit tokens)
        sv.report(comm.ServeResult(node_id=0, request_id=rids[0],
                                   tokens=[1, 2], prefix_hit_tokens=8))
        full_live = json.loads(sv.get(
            comm.ServeReportRequest()).report_json)
        live = full_live["requests"]
        full_forensic = _forensic_report(events_path)
        forensic = full_forensic["requests"]
        for key in ("submitted", "completed", "evicted",
                    "leases_expired"):
            assert forensic[key] == live[key], (key, live, forensic)
        assert forensic["submitted"] == 3
        assert forensic["completed"] == 3
        assert forensic["evicted"] == 0
        assert forensic["leases_expired"] == 2
        # prefix-column agreement: router-ledger hits (accepted
        # completions carrying hit tokens) == worker HIT edges
        assert full_live["prefix"]["hits"] == 2
        assert full_live["prefix"]["saved_prefill_tokens"] == 16
        assert full_forensic["prefix"]["hits"] \
            == full_live["prefix"]["hits"]
        assert full_forensic["prefix"]["saved_prefill_tokens"] \
            == full_live["prefix"]["saved_prefill_tokens"]


# -- the SLO verdict engine ---------------------------------------------------


def _drive_queue(router, n):
    for i in range(n):
        router.submit([1, 2], 4)


class TestServeSLOEngine:
    def test_queue_violation_needs_confirm_windows_then_recovers(self):
        clear_ring()
        process_registry().reset()
        r = RequestRouter(lease_timeout_secs=0)
        eng = ServeSLOEngine(r, queue_depth=2, window_secs=1.0,
                             confirm_windows=2)
        assert eng.enabled()
        _drive_queue(r, 5)  # depth 5 > target 2: burn 2.5
        assert eng.evaluate(now=0.0, force=True) == {}  # 1st over
        assert eng.evaluate(now=0.1) == {}  # inside window: no tick
        verdicts = eng.evaluate(now=1.0)  # 2nd over: confirms
        assert "queue_depth" in verdicts
        ev = verdicts["queue_depth"]["evidence"]
        assert ev["burn_rate"] == pytest.approx(2.5)
        assert len(ev["burn_rates"]) == 2
        assert ev["confirm_windows"] == 2
        tid = verdicts["queue_depth"]["trace_id"]
        viol = [e for e in recent_events()
                if e["kind"] == EventKind.SERVE_SLO_VIOLATION]
        assert viol and viol[-1]["error_code"] == "SERVE_SLO_VIOLATION"
        assert viol[-1]["trace_id"] == tid
        # drain the queue: ONE under window must not clear it...
        for _ in range(5):
            req = r.lease(0, 1)
            r.complete(0, req[0]["request_id"], [1])
        assert eng.evaluate(now=2.0)  # 1st under: still active
        assert eng.evaluate(now=3.0) == {}  # 2nd under: recovered
        rec = [e for e in recent_events()
               if e["kind"] == EventKind.SERVE_SLO_RECOVERED]
        assert rec and rec[-1]["trace_id"] == tid  # one incident id
        assert rec[-1]["violated_seconds"] > 0

    def test_ttft_judged_on_the_rolling_window_not_history(self):
        process_registry().reset()
        r = RequestRouter(lease_timeout_secs=0)
        eng = ServeSLOEngine(r, ttft_p95_secs=0.01, window_secs=1.0,
                             confirm_windows=1)

        def complete(n, ttft):
            for i in range(n):
                rid = r.submit([1], 4)
                r.lease(0, 1)
                r.complete(0, rid, [1, 2], ttft_s=ttft,
                           e2e_s=ttft + 0.01)

        complete(4, 0.10)  # slow history
        assert eng.evaluate(now=0.0, force=True)  # first window: over
        # recovery must come from the WINDOWED p95: fresh fast
        # completions clear it even though the cumulative p95 is
        # still poisoned by the slow history
        complete(8, 0.001)
        assert eng.evaluate(now=1.0) == {}
        # a window with NO new completions holds state (no flap)
        assert eng.evaluate(now=2.0) == {}

    def test_clamped_ttft_is_a_lower_bound_not_a_recovery(self):
        """Observations past the last finite bucket bound clamp to it
        (overflow). A clamped value above target still flags (a lower
        bound over target IS over); a clamped value below target is
        INCONCLUSIVE — it must neither flag under-budget progress nor
        recover an active violation while real TTFT is 10x the
        target."""
        process_registry().reset()
        r = RequestRouter(lease_timeout_secs=0)

        def complete(n, ttft):
            for i in range(n):
                rid = r.submit([1], 4)
                r.lease(0, 1)
                r.complete(0, rid, [1, 2], ttft_s=ttft,
                           e2e_s=ttft + 1)

        # target above the last finite bound (30s): every 300s TTFT
        # clamps to 30.0 <= 40 — without overflow handling this run
        # would read as healthy forever
        eng = ServeSLOEngine(r, ttft_p95_secs=40.0, window_secs=1.0,
                             confirm_windows=1)
        complete(4, 300.0)
        assert eng.evaluate(now=0.0, force=True) == {}  # held, not under
        assert eng._under.get("ttft_p95", 0) == 0  # censored window
        # a clamped lower bound ABOVE target is conclusive: flags,
        # and the evidence says the magnitude is censored
        eng2 = ServeSLOEngine(r, ttft_p95_secs=10.0, window_secs=1.0,
                              confirm_windows=1)
        complete(4, 300.0)
        verdicts = eng2.evaluate(now=0.0, force=True)
        assert "ttft_p95" in verdicts
        assert verdicts["ttft_p95"]["evidence"]["overflow"] is True
        # the active violation must not recover on more censored
        # windows
        complete(4, 300.0)
        assert eng2.evaluate(now=1.0)  # still active

    def test_disabled_targets_never_evaluate(self):
        process_registry().reset()
        r = RequestRouter(lease_timeout_secs=0)
        eng = ServeSLOEngine(r, ttft_p95_secs=0, queue_depth=0,
                             window_secs=0.0)
        _drive_queue(r, 50)
        assert not eng.enabled()
        assert eng.evaluate(force=True) == {}

    def test_listeners_fire_outside_lock_and_survive_breakage(self):
        process_registry().reset()
        r = RequestRouter(lease_timeout_secs=0)
        eng = ServeSLOEngine(r, queue_depth=1, window_secs=1.0,
                             confirm_windows=1)
        seen = []

        def broken(slo, verdict, info):
            raise RuntimeError("boom")

        def listener(slo, verdict, info):
            # re-entering a query under the listener must not deadlock
            # (fired outside the engine lock)
            eng.verdicts()
            seen.append((slo, verdict, info["trace_id"]))

        eng.add_verdict_listener(broken)
        eng.add_verdict_listener(listener)
        _drive_queue(r, 3)
        eng.evaluate(now=0.0, force=True)
        assert seen and seen[0][0] == "queue_depth"
        assert seen[0][1] == "violation" and seen[0][2]


# -- the scale policy ---------------------------------------------------------


class _ScalerStub:
    def __init__(self):
        self.proposals = []
        self.woken = 0

    def submit_serving_proposal(self, p):
        self.proposals.append(p)

    def request_immediate_evaluation(self):
        self.woken += 1


class TestServingScalePolicy:
    def _violate(self, eng, r, now=0.0):
        _drive_queue(r, 4)
        eng.evaluate(now=now, force=True)

    def test_violation_proposes_scale_out_with_cooldown(self):
        clear_ring()
        process_registry().reset()
        r = RequestRouter(lease_timeout_secs=0)
        eng = ServeSLOEngine(r, queue_depth=1, window_secs=1.0,
                             confirm_windows=1)
        scaler = _ScalerStub()
        applied = []
        pol = ServingScalePolicy(eng, auto_scaler=scaler,
                                 apply=applied.append,
                                 cooldown_secs=3600.0)
        self._violate(eng, r)
        assert len(pol.proposals) == 1
        prop = pol.proposals[0]
        assert prop["direction"] == "scale_out"
        assert prop["reason"] == "slo:queue_depth"
        assert prop["trace_id"]  # the violation's incident id
        assert scaler.proposals == [prop] and applied == [prop]
        evs = [e for e in recent_events()
               if e["kind"] == EventKind.SERVE_SCALE_PROPOSED]
        assert evs[-1]["trace_id"] == prop["trace_id"]
        # a second violation inside the cooldown is suppressed
        # (recover first so the engine can re-flag)
        for _ in range(4):
            req = r.lease(0, 1)
            r.complete(0, req[0]["request_id"], [1])
        eng.evaluate(now=1.0)
        self._violate(eng, r, now=2.0)
        assert len(pol.proposals) == 1

    def test_sustained_idle_proposes_scale_in(self):
        process_registry().reset()
        r = RequestRouter(lease_timeout_secs=0)
        store = NodeRuntimeStore()
        store.ingest(_serve_node_report(1, 10, _counts_at(2, 10),
                                        occupancy=0.0))
        eng = ServeSLOEngine(r, queue_depth=1, window_secs=1.0)
        pol = ServingScalePolicy(eng, store=store, cooldown_secs=0.0,
                                 idle_windows=2)
        pol.tick()
        assert not pol.proposals  # one idle tick is not sustained
        pol.tick()
        assert pol.proposals[-1]["direction"] == "scale_in"
        # occupancy back -> the idle counter resets
        store.ingest(_serve_node_report(1, 20, _counts_at(2, 20),
                                        occupancy=2.0))
        pol.tick()
        assert len(pol.proposals) == 1

    def test_job_auto_scaler_records_and_executes_proposals(self):
        from dlrover_tpu.master.node.job_auto_scaler import JobAutoScaler
        from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor

        scaler = JobAutoScaler(job_manager=None, job_optimizer=None,
                               speed_monitor=SpeedMonitor(),
                               interval_secs=3600)
        applied = []
        scaler.attach_serving_apply(applied.append)
        scaler.submit_serving_proposal({"direction": "scale_out",
                                        "reason": "slo:queue_depth"})
        assert scaler.serving_proposals()[0]["direction"] == "scale_out"
        assert applied and applied[0]["reason"] == "slo:queue_depth"
        assert scaler._wake.is_set()  # immediate evaluation requested


# -- serve node series + straggler flavor -------------------------------------


class TestServeNodeSeries:
    def test_serve_reports_export_serve_gauges_not_training_names(self):
        process_registry().reset()
        store = NodeRuntimeStore()
        store.ingest(_serve_node_report(5, 10, _counts_at(5, 10),
                                        tokens=40, occupancy=3,
                                        queue_len=2))
        reg = process_registry()
        labels = {"node": "5"}
        assert reg.get(tm.NODE_SERVE_DECODE_P50, labels=labels)
        assert reg.get(tm.NODE_SERVE_SLOT_OCCUPANCY,
                       labels=labels).value == 3
        assert reg.get(tm.NODE_SERVE_QUEUE_LEN, labels=labels).value == 2
        assert reg.get(tm.NODE_SERVE_SLOTS, labels=labels).value == 4
        # training names must NOT exist for a serve node
        assert reg.get(tm.NODE_STEP_P50, labels=labels) is None
        # tokens/sec needs two samples (absent-not-zero)
        assert reg.get(tm.NODE_SERVE_TOKENS_PER_S,
                       labels=labels) is None
        store.ingest(_serve_node_report(5, 30, _counts_at(5, 30),
                                        tokens=100, occupancy=3))
        rate = reg.get(tm.NODE_SERVE_TOKENS_PER_S, labels=labels)
        assert rate is not None and rate.value > 0
        # the exposition renders the labeled serving family
        text = reg.render_prometheus()
        assert 'dlrover_node_serve_decode_p50_seconds{node="5"}' in text

    def test_slow_decode_worker_gets_serve_flavored_verdict(self):
        clear_ring()
        process_registry().reset()
        store = NodeRuntimeStore()
        det = StragglerDetector(store, ratio=2.0, confirm_windows=2,
                                hang_secs=0)
        for window in range(1, 4):
            store.ingest(_serve_node_report(
                1, 10 * window, _counts_at(2, 10 * window), tokens=10))
            det.observe(1)
            store.ingest(_serve_node_report(
                2, 10 * window, _counts_at(80, 10 * window), tokens=2,
                occupancy=2))
            det.observe(2)
        verdicts = det.verdicts()
        assert 2 in verdicts and verdicts[2]["verdict"] == "straggler"
        ev = verdicts[2]["evidence"]
        assert ev["workload"] == "serve"
        assert ev["ratio"] >= 2.0
        assert "slot_occupancy" in ev
        evs = [e for e in recent_events()
               if e["kind"] == EventKind.DIAG_STRAGGLER]
        assert evs and evs[-1]["workload"] == "serve"

    def test_training_nodes_never_anchor_a_serve_median(self):
        process_registry().reset()
        store = NodeRuntimeStore()
        det = StragglerDetector(store, ratio=2.0, confirm_windows=1,
                                hang_secs=0)
        # one fast TRAINING node + one slow SERVE node: no serve peer
        # exists, so no verdict can form (cross-workload steps are not
        # comparable)
        for window in range(1, 4):
            store.ingest(comm.NodeRuntimeReport(
                node_id=1, timestamp=time.time(),
                step=10 * window, steps_total=float(10 * window),
                bounds=BOUNDS,
                step_time_counts=_counts_at(2, 10 * window)))
            det.observe(1)
            store.ingest(_serve_node_report(
                2, 10 * window, _counts_at(80, 10 * window)))
            det.observe(2)
        assert det.verdicts() == {}


# -- derivations --------------------------------------------------------------


class TestServingScaleDerivations:
    def test_mttr_pairs_violation_with_recovery(self):
        t0 = 1000.0
        events = [
            {"kind": EventKind.SERVE_SLO_VIOLATION, "ts": t0,
             "error_code": "SERVE_SLO_VIOLATION", "pid": 1,
             "mono": 10.0},
            {"kind": EventKind.SERVE_SLO_RECOVERED, "ts": t0 + 12.5,
             "pid": 1, "mono": 22.5},
        ]
        incidents = [i for i in derive_incidents(events)
                     if i["scenario"] == "serving_scale"]
        assert len(incidents) == 1
        assert incidents[0]["recovery_seconds"] == pytest.approx(12.5)

    def test_goodput_books_serving_scale_without_stealing(self):
        t0 = 1000.0
        events = [
            {"kind": "job_start", "ts": t0},
            {"kind": EventKind.SERVE_SLO_VIOLATION, "ts": t0 + 1,
             "error_code": "SERVE_SLO_VIOLATION"},
            {"kind": EventKind.SERVE_RESIZE_BEGIN, "ts": t0 + 2},
            {"kind": EventKind.SERVE_RESIZE_DONE, "ts": t0 + 4},
            {"kind": EventKind.SERVE_SLO_RECOVERED, "ts": t0 + 7},
            {"kind": "job_end", "ts": t0 + 10},
        ]
        buckets = derive_goodput(events)["detail"]["buckets"]
        # the resize pause stays reshard-class; serving_scale claims
        # only the rest of the violation window (lowest priority)
        assert buckets["reshard"]["seconds"] == pytest.approx(2.0)
        assert buckets["serving_scale"]["seconds"] == pytest.approx(
            4.0)  # (t0+1..t0+7) minus the 2s reshard claim
        total = sum(b["seconds"] for b in buckets.values())
        assert total == pytest.approx(10.0, rel=0.01)

    def test_slot_ledger_derivation_dedups_cumulative_reports(self):
        ledger1 = {"decode": 2.0, "prefill": 1.0, "admitted_idle": 0.0,
                   "vacant": 1.0, "resize_frozen": 0.0}
        ledger2 = {k: v * 2 for k, v in ledger1.items()}
        events = [
            # one executor's cumulative ledger reported twice: the
            # later SERVE_END supersedes
            {"kind": EventKind.SERVE_END, "ts": 1.0, "pid": 7,
             "node": "0", "serve_seq": 1, "slot_ledger": ledger1,
             "slot_seconds": 4.0},
            {"kind": EventKind.SERVE_END, "ts": 2.0, "pid": 7,
             "node": "0", "serve_seq": 1, "slot_ledger": ledger2,
             "slot_seconds": 8.0},
            # a second executor in the same pid: summed
            {"kind": EventKind.SERVE_END, "ts": 3.0, "pid": 7,
             "node": "0", "serve_seq": 2, "slot_ledger": ledger1,
             "slot_seconds": 4.0},
        ]
        out = derive_slot_ledger(events)
        assert out["runs"] == 2
        assert out["slot_seconds"] == pytest.approx(12.0)
        assert out["buckets"]["decode"]["seconds"] == pytest.approx(6.0)
        assert out["coverage"] == pytest.approx(1.0)


# -- wedge A: SLO verdict -> proposal -> live resize, in-process --------------


class TestServeSLOWedge:
    def test_slow_worker_trips_slo_scaler_acts_ledger_balances(
            self, engine, tiny_params, tmp_path, monkeypatch):
        """Real router + two serve workers over RPC, worker 2 decoding
        30ms/step: serve {node=} gauges land on the master registry,
        the queue-depth SLO confirms a SERVE_SLO_VIOLATION with
        burn-rate evidence, the scale proposal reaches the auto-scaler
        under the SAME trace id and — stubbed to the existing resize
        path — live-resizes the worker 8 -> 4 mid-traffic, the
        straggler detector names the slow worker with serve-flavored
        evidence, and the slot-seconds ledger sums to slots x wall
        within 1%."""
        from dlrover_tpu.master.node.job_auto_scaler import JobAutoScaler

        events_path = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", events_path)
        ctx = get_context()
        monkeypatch.setattr(ctx, "serve_slo_queue_depth", 1.0)
        monkeypatch.setattr(ctx, "serve_slo_window_secs", 0.02)
        monkeypatch.setattr(ctx, "serve_slo_confirm_windows", 2)
        clear_ring()
        process_registry().reset()
        master = start_local_master()
        try:
            scaler = JobAutoScaler(
                job_manager=None, job_optimizer=None,
                speed_monitor=master.speed_monitor,
                interval_secs=3600)
            master.servicer.serving_scale_policy.attach_auto_scaler(
                scaler)

            # worker 1: the fast peer (the module engine), bounded run
            reg_b = MetricsRegistry()
            client_b = MasterClient(master.addr, node_id=1)
            worker_b = ServeExecutor(
                engine, router_client=client_b, serve_window=1,
                plan_poll_secs=0, registry=reg_b,
                report_hook=ServeRuntimeReportHook(
                    client_b, every_steps=1, min_interval_s=0,
                    registry=reg_b))
            sub = MasterClient(master.addr, node_id=99)
            for i in range(3):
                sub.submit_serve_request(_prompt(4, seed=i),
                                         max_new_tokens=4,
                                         request_id=f"warm{i}")
            worker_b.serve()
            assert worker_b.completed

            # worker 2: slow decode (30ms/step), own engine so the
            # resize cannot disturb the module fixture
            eng_a = ServeEngine(
                TINY, strategy=Strategy(mesh=MeshPlan(data=-1),
                                        rule_set="llama"),
                serve_slots=2, prefill_chunk=4, max_seq=32,
                page_size=8)
            eng_a.prepare(tiny_params)
            survivors = jax.devices()[:4]
            eng_a.prewarm(devices=survivors)

            def make_slow(fn):
                def slow_decode(*a):
                    time.sleep(0.03)
                    return fn(*a)

                return slow_decode

            # the worker is slow on EVERY topology (the prewarmed
            # survivor program too) — the injected fault is the box,
            # not one compiled program
            for prog in eng_a._programs.values():
                prog.decode = make_slow(prog.decode)
            reg_a = MetricsRegistry()
            client_a = MasterClient(master.addr, node_id=2)
            worker_a = ServeExecutor(
                eng_a, router_client=client_a, serve_window=1,
                plan_poll_secs=0, registry=reg_a,
                report_hook=ServeRuntimeReportHook(
                    client_a, every_steps=1, min_interval_s=0,
                    registry=reg_a))

            # the stubbed actuator: the existing lease-holding
            # live-resize path on the running worker
            def apply_proposal(p):
                worker_a.request_resize(survivors,
                                        trace_id=p["trace_id"])

            scaler.attach_serving_apply(apply_proposal)

            for i in range(10):
                sub.submit_serve_request(_prompt(5, seed=50 + i),
                                         max_new_tokens=4,
                                         request_id=f"q{i}")
            t_serve = threading.Thread(target=worker_a.serve)
            t_serve.start()
            slo = master.servicer.serve_slo
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if slo.evaluate(force=True):
                    break
                time.sleep(0.02)
            verdicts = slo.verdicts()
            assert "queue_depth" in verdicts, "SLO never confirmed"
            tid = verdicts["queue_depth"]["trace_id"]
            ev = verdicts["queue_depth"]["evidence"]
            assert ev["burn_rate"] > 1.0 and len(ev["burn_rates"]) >= 2
            t_serve.join(timeout=30)
            assert not t_serve.is_alive()
            # drain the recovery (queue empty now)
            for _ in range(3):
                slo.evaluate(force=True)
                time.sleep(0.01)
            assert slo.verdicts() == {}, "SLO never recovered"

            # the auto-scaler received AND acted on the proposal
            props = scaler.serving_proposals()
            assert props and props[0]["direction"] == "scale_out"
            assert props[0]["trace_id"] == tid
            records = read_events(events_path)
            resized = [r for r in records
                       if r["kind"] == EventKind.SERVE_RESIZE_DONE]
            assert resized and resized[-1]["world_to"] == 4
            assert resized[-1].get("trace_id") == tid  # one incident
            assert resized[-1]["recompiled"] == 0  # prewarmed
            # zero dropped across it all
            report = sub.get_serve_report()
            assert report["requests"]["completed"] == 13
            assert report["requests"]["dropped"] == 0

            # serve {node=} gauges on the master registry (= /metrics)
            text = process_registry().render_prometheus()
            assert 'dlrover_node_serve_decode_p50_seconds{node="1"}' \
                in text
            assert 'dlrover_node_serve_decode_p50_seconds{node="2"}' \
                in text
            # the straggler detector names the slow decode worker with
            # serve-flavored evidence
            diag = master.servicer.straggler_detector.verdicts()
            assert 2 in diag, diag
            assert diag[2]["evidence"]["workload"] == "serve"

            # the slot-seconds ledger sums to slots x wall within 1%
            led = worker_a.slot_ledger()
            classes = sum(v for k, v in led.items()
                          if k not in ("slot_seconds", "serve_wall_s"))
            assert classes == pytest.approx(led["slot_seconds"],
                                            rel=1e-6)
            assert led["slot_seconds"] == pytest.approx(
                2 * led["serve_wall_s"], rel=0.01)
            assert led["resize_frozen"] > 0  # the resize pause is seen
            derived = derive_slot_ledger(records)
            assert derived["coverage"] == pytest.approx(1.0, abs=0.01)

            # mttr derives the serving_scale scenario, recovered
            incidents = [i for i in derive_incidents(records)
                         if i["scenario"] == "serving_scale"]
            assert incidents
            assert incidents[-1]["recovery_seconds"] is not None

            # the CLI views work on the same timeline
            from dlrover_tpu.trainer.run import main as tpurun
            import io

            buf, prev = io.StringIO(), sys.stdout
            sys.stdout = buf
            try:
                rc = tpurun(["serve", "slo", "--events", events_path,
                             "--json"])
            finally:
                sys.stdout = prev
            assert rc == 0
            out = json.loads(buf.getvalue())
            assert out["violations"][0]["slo"] == "queue_depth"
            assert out["ledger"]["coverage"] == pytest.approx(
                1.0, abs=0.01)
            assert out["scale_proposals"][0]["direction"] == "scale_out"

            buf, prev = io.StringIO(), sys.stdout
            sys.stdout = buf
            try:
                rc = tpurun(["serve", "slo", "--addr", master.addr,
                             "--json"])
            finally:
                sys.stdout = prev
            assert rc == 0
            live = json.loads(buf.getvalue())
            assert live["targets"]["queue_depth"] == 1.0
            assert live["proposals"][0]["direction"] == "scale_out"
            client_a.close()
            client_b.close()
            sub.close()
        finally:
            master.stop()


# -- wedge B: one request's lifecycle across >= 2 pids ------------------------


class TestRequestTraceAcrossPids:
    def test_merged_trace_renders_request_lane_spanning_two_pids(
            self, tmp_path, monkeypatch):
        """A subprocess serve worker (tpurun serve) against an
        in-process master: the request trace id minted at
        Router.submit rides the lease wire and the completion RPC, so
        the merged Perfetto view holds one lane per request whose
        lifecycle events span the router pid AND the worker pid."""
        from dlrover_tpu.telemetry.correlate import merged_trace_events

        events_path = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", events_path)
        clear_ring()
        process_registry().reset()
        master = start_local_master()
        try:
            sub = MasterClient(master.addr, node_id=99)
            for i in range(2):
                sub.submit_serve_request(_prompt(4, seed=i),
                                         max_new_tokens=3,
                                         request_id=f"x{i}")
            env = dict(os.environ, DLROVER_TPU_EVENTS_FILE=events_path)
            proc = subprocess.run(
                [sys.executable, "-m", "dlrover_tpu.serving.cli",
                 "serve", "--addr", master.addr, "--node_id", "7",
                 "--max_seq", "32"],
                env=env, capture_output=True, text=True, timeout=240)
            assert proc.returncode == 0, proc.stderr[-2000:]
            report = sub.get_serve_report()
            assert report["requests"]["completed"] == 2
            records = read_events(events_path)
            by_tid = {}
            for r in records:
                if r.get("trace_id", "").startswith("req-"):
                    by_tid.setdefault(r["trace_id"], []).append(r)
            assert len(by_tid) == 2
            for tid, chain in by_tid.items():
                kinds = [r["kind"] for r in chain]
                pids = {r["pid"] for r in chain}
                assert len(pids) >= 2, (tid, kinds)  # router + worker
                for kind in (EventKind.SERVE_REQUEST_SUBMITTED,
                             EventKind.SERVE_REQUEST_LEASED,
                             EventKind.SERVE_PREFILL_CHUNK,
                             EventKind.SERVE_FIRST_TOKEN,
                             EventKind.SERVE_REQUEST_DONE,
                             EventKind.SERVE_REQUEST_COMPLETED):
                    assert kind in kinds, (tid, kinds)
            lanes = [e for e in merged_trace_events(records)
                     if e.get("cat") == "serve_request"]
            assert len(lanes) == 2
            for lane in lanes:
                assert len(lane["args"]["pids"]) >= 2
                assert lane["args"]["lifecycle"][0] == \
                    EventKind.SERVE_REQUEST_SUBMITTED
            # forensic and live requests CLIs agree on this run too
            from dlrover_tpu.serving.cli import _forensic_report

            forensic = _forensic_report(events_path)["requests"]
            assert forensic["submitted"] == 2
            assert forensic["completed"] == 2
            assert forensic["leases_expired"] == 0
            sub.close()
        finally:
            master.stop()


# -- overhead gate ------------------------------------------------------------


class TestServeObservabilityOverhead:
    def test_serving_observability_overhead_within_5pct(self, engine):
        """Min-of-medians paired gate (the PR 9 methodology): serve
        legs with the full SLO plane on (events + request tracing +
        ledger + histograms) vs telemetry off, alternating order,
        median of 3 pairs, best of up to 3 attempts ≤ 1.05."""
        ctx = get_context()

        def leg(enabled):
            ctx.telemetry_enabled = enabled
            engine.cache = engine.fresh_cache()
            ex = ServeExecutor(engine, serve_window=1)
            for i in range(6):
                ex.submit(_prompt(5, seed=i), max_new_tokens=4)
            t0 = time.perf_counter()
            ex.serve()
            return time.perf_counter() - t0

        leg(True)
        leg(False)  # both modes warm before any timed pair
        medians = []
        for attempt in range(3):
            ratios = []
            for i in range(3):
                if (attempt + i) % 2 == 0:
                    on, off = leg(True), leg(False)
                else:
                    off, on = leg(False), leg(True)
                ratios.append(on / off)
            medians.append(sorted(ratios)[1])
            if min(medians) <= 1.05:
                break
        assert min(medians) <= 1.05, medians
