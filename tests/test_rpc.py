"""RPC transport test: real gRPC server + client with the JSON codec."""

import pytest

from dlrover_tpu.common import comm
from dlrover_tpu.rpc.client import RpcChannel
from dlrover_tpu.rpc.server import addr_connectable, build_server


class EchoServicer:
    def get(self, request, context):
        if isinstance(request, comm.KVStoreGetRequest):
            return comm.KVStoreValue(key=request.key, value="hello", found=True)
        return comm.Response(success=False, reason="unhandled")

    def report(self, request, context):
        self.last = request
        return comm.Response(success=True)


@pytest.fixture
def server():
    servicer = EchoServicer()
    srv, port = build_server(servicer, port=0, max_workers=4)
    srv.start()
    yield servicer, f"127.0.0.1:{port}"
    srv.stop(0)


def test_get_and_report(server):
    servicer, addr = server
    chan = RpcChannel(addr, timeout=5.0)
    val = chan.get(comm.KVStoreGetRequest(key="k1"))
    assert isinstance(val, comm.KVStoreValue) and val.value == "hello"

    resp = chan.report(comm.GlobalStep(step=10, timestamp=1.0))
    assert resp.success
    assert servicer.last.step == 10
    chan.close()


def test_addr_connectable(server):
    _, addr = server
    assert addr_connectable(addr)
    assert not addr_connectable("127.0.0.1:1")
