"""Model family tests on the 8-device CPU mesh through accelerate()."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models import deepfm, gpt2, llama, mnist_cnn
from dlrover_tpu.parallel.accelerate import accelerate
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy


def _lm_batch(b=4, s=32, vocab=256, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, vocab, size=(b, s + 1))
    return {
        "input_ids": jnp.asarray(ids[:, :-1]),
        "labels": jnp.asarray(ids[:, 1:]),
    }


class TestLlama:
    def test_forward_shapes(self):
        cfg = llama.llama_tiny()
        params = llama.init(jax.random.PRNGKey(0), cfg)
        logits, aux = llama.apply(
            params, jnp.zeros((2, 16), jnp.int32), cfg
        )
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self):
        """Changing a future token must not affect past logits."""
        cfg = llama.llama_tiny(remat_policy="none")
        params = llama.init(jax.random.PRNGKey(0), cfg)
        ids = jnp.zeros((1, 16), jnp.int32)
        ids2 = ids.at[0, 10].set(7)
        l1, _ = llama.apply(params, ids, cfg)
        l2, _ = llama.apply(params, ids2, cfg)
        np.testing.assert_allclose(l1[0, :10], l2[0, :10], atol=1e-5)
        assert not np.allclose(l1[0, 10:], l2[0, 10:], atol=1e-5)

    def test_packed_segments_equal_separate_documents(self):
        """The packed-sequence contract end to end through the model:
        two documents packed into one row (segment masking + RoPE
        positions restarting per segment) produce EXACTLY the logits
        each document gets in its own row."""
        cfg = llama.llama_tiny(remat_policy="none")
        params = llama.init(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(0)
        doc_a = rng.randint(0, cfg.vocab_size, (1, 10))
        doc_b = rng.randint(0, cfg.vocab_size, (1, 22))

        packed_ids = jnp.asarray(
            np.concatenate([doc_a, doc_b], axis=1))
        seg = jnp.asarray([[0] * 10 + [1] * 22])
        packed, _ = llama.apply(params, packed_ids, cfg, segment_ids=seg)

        alone_a, _ = llama.apply(params, jnp.asarray(doc_a), cfg)
        alone_b, _ = llama.apply(params, jnp.asarray(doc_b), cfg)
        np.testing.assert_allclose(packed[0, :10], alone_a[0],
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(packed[0, 10:], alone_b[0],
                                   atol=2e-5, rtol=2e-5)

    def test_segment_positions(self):
        seg = jnp.asarray([[0, 0, 0, 1, 1, 2, 2, 2]])
        pos = llama.segment_positions(seg)
        np.testing.assert_array_equal(
            np.asarray(pos), [[0, 1, 2, 0, 1, 0, 1, 2]])

    def test_packed_loss_fn_trains(self):
        import optax

        from dlrover_tpu.parallel.accelerate import accelerate
        from dlrover_tpu.parallel.mesh import MeshPlan
        from dlrover_tpu.parallel.strategy import Strategy

        cfg = llama.llama_tiny()
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 32)))
        seg = jnp.asarray(
            np.sort(rng.randint(0, 3, (4, 32)), axis=1))
        labels = jnp.where(
            jnp.concatenate(
                [seg[:, :-1] == seg[:, 1:],
                 jnp.zeros((4, 1), bool)], axis=1),
            jnp.concatenate([ids[:, 1:], ids[:, :1]], axis=1), -100)
        batch = {"input_ids": ids, "labels": labels, "segment_ids": seg}
        result = accelerate(
            llama.make_init_fn(cfg), llama.make_loss_fn(cfg),
            optax.adam(1e-3), batch,
            strategy=Strategy(mesh=MeshPlan(data=2, fsdp=2, tensor=2),
                              rule_set="llama"),
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        sb = result.shard_batch(batch)
        losses = []
        for i in range(12):
            state, m = result.train_step(state, sb, jax.random.PRNGKey(i))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0] * 0.8

    def test_trains_through_accelerate_tensor_parallel(self):
        cfg = llama.llama_tiny()
        result = accelerate(
            llama.make_init_fn(cfg), llama.make_loss_fn(cfg),
            optax.adamw(1e-3), _lm_batch(),
            strategy=Strategy(mesh=MeshPlan(data=2, fsdp=2, tensor=2),
                              rule_set="llama"),
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        batch = result.shard_batch(_lm_batch())
        losses = []
        for i in range(10):
            state, m = result.train_step(state, batch, jax.random.PRNGKey(i))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]

    def test_stacked_params_sharded_on_tensor_axis(self):
        cfg = llama.llama_tiny()
        result = accelerate(
            llama.make_init_fn(cfg), llama.make_loss_fn(cfg),
            optax.adamw(1e-3), _lm_batch(),
            strategy=Strategy(mesh=MeshPlan(data=2, tensor=4),
                              rule_set="llama"),
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        qk = state.params["layers"]["q_proj"]["kernel"]  # [2, 64, 64]
        shard = qk.addressable_shards[0].data.shape
        assert shard[2] == qk.shape[2] // 4  # tensor-sharded output dim

    def test_gqa_kv_heads(self):
        cfg = llama.llama_tiny(num_kv_heads=1)
        params = llama.init(jax.random.PRNGKey(0), cfg)
        logits, _ = llama.apply(params, jnp.zeros((1, 8), jnp.int32), cfg)
        assert logits.shape[-1] == cfg.vocab_size

    def test_moe_variant_trains(self):
        cfg = llama.llama_tiny(num_experts=4)
        result = accelerate(
            llama.make_init_fn(cfg), llama.make_loss_fn(cfg),
            optax.adamw(1e-3), _lm_batch(b=8),
            strategy=Strategy(mesh=MeshPlan(data=4, fsdp=2),
                              rule_set="llama"),
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        batch = result.shard_batch(_lm_batch(b=8))
        state, m = result.train_step(state, batch, jax.random.PRNGKey(0))
        assert np.isfinite(float(m["loss"]))

    def test_chunked_head_loss_matches_full(self):
        cfg = llama.llama_tiny()
        params = llama.init(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 64)))
        labels = jnp.where(jnp.asarray(rng.rand(2, 64)) < 0.9, ids, -100)
        batch = {"input_ids": ids, "labels": labels}
        key = jax.random.PRNGKey(1)
        full, _ = llama.make_loss_fn(cfg)(params, batch, key)
        chunked, _ = llama.make_loss_fn(cfg, head_chunk=16)(
            params, batch, key
        )
        np.testing.assert_allclose(float(full), float(chunked), rtol=1e-5)
        # gradients agree too (the checkpointed scan recomputes logits)
        gf = jax.grad(lambda p: llama.make_loss_fn(cfg)(p, batch, key)[0])(
            params
        )
        gc = jax.grad(
            lambda p: llama.make_loss_fn(cfg, head_chunk=16)(
                p, batch, key
            )[0]
        )(params)
        np.testing.assert_allclose(
            np.asarray(gf["lm_head"]["kernel"]),
            np.asarray(gc["lm_head"]["kernel"]), atol=1e-5, rtol=1e-4,
        )

    def test_param_count_7b_in_range(self):
        n = llama.param_count(llama.llama2_7b())
        assert 6.5e9 < n < 7.5e9

    def test_param_count_llama3_8b_in_range(self):
        n = llama.param_count(llama.llama3_8b())
        assert 7.8e9 < n < 8.3e9
        cfg = llama.llama3_8b()
        assert cfg.num_heads // cfg.num_kv_heads == 4  # GQA group of 4

    def test_param_count_llama3_70b_in_range(self):
        n = llama.param_count(llama.llama3_70b())
        assert 69e9 < n < 72e9
        cfg = llama.llama3_70b()
        assert cfg.num_heads // cfg.num_kv_heads == 8


class TestGPT2:
    def test_forward_and_tied_head(self):
        cfg = gpt2.gpt2_tiny()
        params = gpt2.init(jax.random.PRNGKey(0), cfg)
        logits = gpt2.apply(params, jnp.zeros((2, 16), jnp.int32), cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert "lm_head" not in params  # tied to embed_tokens

    def test_trains_through_accelerate(self):
        cfg = gpt2.gpt2_tiny()
        result = accelerate(
            gpt2.make_init_fn(cfg), gpt2.make_loss_fn(cfg),
            optax.adamw(1e-3), _lm_batch(b=8),
            strategy=Strategy(mesh=MeshPlan(data=4, fsdp=2)),
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        batch = result.shard_batch(_lm_batch(b=8))
        losses = []
        for i in range(8):
            state, m = result.train_step(state, batch, jax.random.PRNGKey(i))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]


class TestMnist:
    def test_trains(self):
        rng = np.random.RandomState(0)
        batch = {
            "image": jnp.asarray(rng.randn(16, 28, 28, 1), jnp.float32),
            "label": jnp.asarray(rng.randint(0, 10, (16,))),
        }
        result = accelerate(
            lambda r: mnist_cnn.init(r), mnist_cnn.make_loss_fn(),
            optax.adam(1e-3), batch,
            strategy=Strategy(mesh=MeshPlan(data=8)),
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        b = result.shard_batch(batch)
        losses = []
        for i in range(10):
            state, m = result.train_step(state, b, jax.random.PRNGKey(i))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]


class TestDeepFM:
    def test_trains(self):
        cfg = deepfm.deepfm_tiny()
        rng = np.random.RandomState(0)
        batch = {
            "sparse": jnp.asarray(
                rng.randint(0, cfg.vocab_size, (32, cfg.num_sparse_features))
            ),
            "dense": jnp.asarray(
                rng.rand(32, cfg.num_dense_features), jnp.float32
            ),
            "label": jnp.asarray(rng.randint(0, 2, (32,))),
        }
        result = accelerate(
            deepfm.make_init_fn(cfg), deepfm.make_loss_fn(cfg),
            optax.adagrad(0.05), batch,
            strategy=Strategy(mesh=MeshPlan(data=4, fsdp=2)),
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        b = result.shard_batch(batch)
        losses = []
        for i in range(15):
            state, m = result.train_step(state, b, jax.random.PRNGKey(i))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]

    def test_embedding_sharded_on_fsdp(self):
        cfg = deepfm.deepfm_tiny()
        rng = np.random.RandomState(0)
        batch = {
            "sparse": jnp.asarray(rng.randint(0, 128, (8, 4))),
            "dense": jnp.asarray(rng.rand(8, 3), jnp.float32),
            "label": jnp.asarray(rng.randint(0, 2, (8,))),
        }
        result = accelerate(
            deepfm.make_init_fn(cfg), deepfm.make_loss_fn(cfg),
            optax.adam(1e-3), batch,
            strategy=Strategy(mesh=MeshPlan(data=1, fsdp=8)),
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        table = state.params["embedding"]["table"]  # [128, 8]
        assert table.addressable_shards[0].data.shape[0] == 16


class TestGPT2Pipelined:
    """GPT-2 joins the pipelined decoder families (shared
    dispatch_pipeline formulation; tied head spread over pipe)."""

    def test_pipelined_matches_apply(self):
        cfg = gpt2.gpt2_tiny(num_layers=4)
        params = gpt2.init(jax.random.PRNGKey(0), cfg)
        ids = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 16))
        )
        plain = gpt2.apply(params, ids, cfg)
        piped = gpt2.apply_pipelined(
            params, ids, cfg, num_stages=2, num_microbatches=2
        )
        np.testing.assert_allclose(np.asarray(piped), np.asarray(plain),
                                   rtol=2e-4, atol=2e-4)

    def test_uneven_interleaved_matches_apply(self):
        cfg = gpt2.gpt2_tiny(num_layers=6)
        params = gpt2.init(jax.random.PRNGKey(0), cfg)
        ids = jnp.asarray(
            np.random.RandomState(1).randint(0, cfg.vocab_size, (4, 16))
        )
        plain = gpt2.apply(params, ids, cfg)
        piped = gpt2.apply_pipelined(
            params, ids, cfg, num_stages=2, num_microbatches=2,
            num_virtual=2, stage_depths=(2, 1, 2, 1),
        )
        np.testing.assert_allclose(np.asarray(piped), np.asarray(plain),
                                   rtol=2e-4, atol=2e-4)

    # budget triage (PR 16): pp-rule composition stays pinned tier-1 by
    # the llama/neox/glm pipelined tests and gpt2's apply-level parity;
    # this trains run rides slow
    @pytest.mark.slow
    def test_trains_with_gpt2_pp_rules_on_mesh(self):
        import optax

        from dlrover_tpu.parallel.accelerate import accelerate
        from dlrover_tpu.parallel.mesh import MeshPlan
        from dlrover_tpu.parallel.strategy import Strategy

        cfg = gpt2.gpt2_tiny(num_layers=4)

        def loss_fn(params, batch, rng):
            from dlrover_tpu.models.losses import masked_lm_loss

            logits = gpt2.apply_pipelined(
                params, batch["input_ids"], cfg,
                num_stages=2, num_microbatches=2,
            )
            return masked_lm_loss(logits, batch["labels"]), {}

        batch = {
            "input_ids": jax.random.randint(
                jax.random.PRNGKey(0), (8, 16), 0, cfg.vocab_size
            ),
            "labels": jax.random.randint(
                jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size
            ),
        }
        strategy = Strategy(
            mesh=MeshPlan(pipe=2, data=2, tensor=2), rule_set="gpt2_pp"
        )
        result = accelerate(
            gpt2.make_init_fn(cfg), loss_fn,
            optax.adam(1e-2), batch, strategy=strategy,
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        sharded = result.shard_batch(batch)
        losses = []
        for i in range(3):
            state, metrics = result.train_step(
                state, sharded, jax.random.PRNGKey(i)
            )
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]
