"""Deviceless AOT compile-and-fit proofs on virtual TPU topologies.

The BASELINE "Llama-2-7B on v5p-32" viability proof runs with no TPU at
all: XLA's TPU compiler is hermetic, so the full jitted train step is
compiled against a ``TopologyDescription`` and memory/cost analysis read
back (``parallel/aot.py``). Committed artifact: ``AOT_7B.json``.
"""

import functools

import jax
import jax.numpy as jnp
import pytest

from dlrover_tpu.models import llama
from dlrover_tpu.parallel.aot import (
    KNOWN_TOPOLOGIES,
    aot_compile_train_step,
)
from dlrover_tpu.parallel.mesh import MeshPlan


@functools.lru_cache(maxsize=1)
def _mosaic_lse_kernels_supported() -> bool:
    """Capability probe: whether THIS jax/Mosaic toolchain can lower
    the flash lse kernel family (prefix + segmented-pair — the ring's
    merge path) for a TPU target. Some toolchains reject the kernels'
    row-bound compare with a verifier error ('arith.cmpi' op requires
    all operands to have the same type, scalar-vs-vector) — a
    TOOLCHAIN gap, not a repo regression, so the deviceless AOT tests
    that force these kernels through Mosaic skip instead of failing
    the box. Probed once per session on tiny shapes (~2 compiles)."""
    import numpy as np  # noqa: F401 — parity with the tests' imports

    from jax.experimental import topologies
    from jax.sharding import SingleDeviceSharding

    from dlrover_tpu.ops.flash_attention import (
        flash_attention_prefix_lse,
        flash_attention_segmented_pair_lse,
    )
    from dlrover_tpu.parallel.aot import _get_topology_desc_serialized

    try:
        topo = _get_topology_desc_serialized(topologies, "v5:2x2x1")
        sh = SingleDeviceSharding(list(topo.devices)[0])
        q = jax.ShapeDtypeStruct((1, 1, 128, 64), jnp.float32)
        plen = jax.ShapeDtypeStruct((1,), jnp.int32)
        seg = jax.ShapeDtypeStruct((1, 128), jnp.int32)
        jax.jit(
            lambda a, b, c, p: flash_attention_prefix_lse(
                a, b, c, p, None, 32, 32, False),
            in_shardings=(sh, sh, sh, sh),
        ).lower(q, q, q, plen).compile()
        jax.jit(
            lambda a, b, c, sq, sk: flash_attention_segmented_pair_lse(
                a, b, c, sq, sk, True, None, 32, 32, False),
            in_shardings=(sh, sh, sh, sh, sh),
        ).lower(q, q, q, seg, seg).compile()
        return True
    except Exception as e:  # noqa: BLE001 — any lowering error = skip
        print(f"Mosaic lse kernels unsupported on this toolchain: "
              f"{type(e).__name__}: {str(e)[:200]}")
        return False


def test_tiny_llama_compiles_on_virtual_v5p_slice():
    config = llama.llama_tiny(use_flash=False)
    report = aot_compile_train_step(
        config, topology="v5p-16", tpu_gen="v5p", global_batch=16,
        model_name="llama_tiny",
    )
    assert report.n_devices == 8  # v5p-16 = 16 cores = 8 chips
    assert report.fits
    assert report.hbm_per_device_bytes < 1e9
    assert report.flops_per_step > 0
    assert report.params == llama.param_count(config)


def test_known_topology_aliases_cover_v5p_sizes():
    assert KNOWN_TOPOLOGIES["v5p-32"] == "v5:2x2x4"


@pytest.mark.slow
def test_tiny_moe_and_packed_ring_compile_deviceless():
    """The round-4 prover modes at test scale: switch-MoE with the moe
    rule set, and packed documents flowing through the ring with the
    segmented pair kernel — both against a virtual topology."""
    if not _mosaic_lse_kernels_supported():
        pytest.skip("Mosaic verifier rejects the flash lse kernels on "
                    "this toolchain (arith.cmpi operand types)")
    moe = llama.llama_tiny(use_flash=False, num_experts=4, moe_top_k=1)
    report = aot_compile_train_step(
        moe, topology="v5p-16", tpu_gen="v5p", global_batch=16,
        rule_set="moe", model_name="llama_tiny+moe4",
        mesh_plan=MeshPlan(data=2, fsdp=2, tensor=2),
    )
    assert report.fits and report.params == llama.param_count(moe)

    ring_cfg = llama.llama_tiny(
        use_flash=True, flash_interpret=False,  # force Mosaic lowering
        flash_block_q=64, flash_block_k=64,
    )
    report = aot_compile_train_step(
        ring_cfg, topology="v5p-16", tpu_gen="v5p", global_batch=16,
        mesh_plan=MeshPlan(fsdp=2, seq=2, tensor=2),
        ring=True, packed_doc_len=32, model_name="llama_tiny+ring",
    )
    assert report.fits


@pytest.mark.slow
def test_glm_prefix_ring_lowers_to_mosaic_deviceless():
    """The prefix-LM ring's production path — prefix kernel on the
    diagonal, pair kernel on visible future shards, inside shard_map —
    lowers to a real TPU executable with no devices. Pins that
    sequence-parallel prefix-LM is not an interpret-mode-only trick."""
    if not _mosaic_lse_kernels_supported():
        pytest.skip("Mosaic verifier rejects the flash lse kernels on "
                    "this toolchain (arith.cmpi operand types)")
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.experimental import topologies

    from dlrover_tpu.models import glm
    from dlrover_tpu.parallel.accelerate import accelerate
    from dlrover_tpu.parallel.aot import _get_topology_desc_serialized
    from dlrover_tpu.parallel.strategy import Strategy

    topo = _get_topology_desc_serialized(topologies, "v5:2x2x2")
    devices = list(topo.devices)
    plan = MeshPlan(data=2, seq=2, tensor=2)
    cfg = glm.glm_tiny(
        use_flash=True, flash_interpret=False,  # force Mosaic
        flash_block_q=32, flash_block_k=32,
        seq_axis="seq", mesh=plan.build(devices),
    )
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (8, 65))
    batch = {
        "input_ids": jnp.asarray(ids[:, :-1]),
        "labels": jnp.asarray(ids[:, 1:]),
        "prefix_len": jnp.asarray([17, 23, 40, 9, 5, 60, 33, 12],
                                  jnp.int32),
    }
    result = accelerate(
        glm.make_init_fn(cfg), glm.make_loss_fn(cfg),
        optax.adafactor(1e-3), batch,
        strategy=Strategy(mesh=plan, rule_set="glm",
                          remat_policy="none"),
        devices=devices,
    )
    abstract_state = jax.eval_shape(result.init_fn, jax.random.PRNGKey(0))
    abstract_batch = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), batch
    )
    compiled = result.train_step.lower(
        abstract_state, abstract_batch,
        jax.ShapeDtypeStruct((2,), jnp.uint32),
    ).compile()
    assert compiled.memory_analysis().temp_size_in_bytes >= 0


@pytest.mark.slow
def test_llama2_7b_fits_v5p_32():
    """The BASELINE row: real 7B config, 16-chip v5p-32, the artifact's
    mesh (data=8 x tensor=2 — AOT_7B.json), PRODUCTION attention path
    (Pallas flash — the hermetic TPU compiler lowers it deviceless)
    with dots_saveable remat. Asserts HBM fit via compiled
    memory_analysis — no hardware involved."""
    config = llama.llama2_7b(
        max_seq_len=4096,
        param_dtype=jnp.bfloat16,
        compute_dtype=jnp.bfloat16,
        remat_policy="dots_saveable",
        use_flash=True,
    )
    report = aot_compile_train_step(
        config, topology="v5p-32", tpu_gen="v5p", global_batch=16,
        mesh_plan=MeshPlan(data=8, fsdp=1, seq=1, tensor=2),
        model_name="llama2_7b",
    )
    assert report.n_devices == 16
    assert report.params > 6.7e9
    assert report.fits, (
        f"7B must fit v5p-32: {report.hbm_per_device_bytes / 1e9:.1f} GB "
        f"of {report.hbm_capacity_bytes / 1e9:.0f} GB"
    )
    # at least ~75% headroom consumed by state+activations is expected
    # to stay under capacity with margin
    assert report.hbm_per_device_bytes < 0.5 * report.hbm_capacity_bytes
    # both bounds: the target AND physical sanity (round-2 artifact
    # claimed 1.31 — an uncalibrated cost model must never pass again)
    assert 0.45 <= report.predicted_mfu < 1.0
    # cross-check the hand-rolled XLA memory sum against the planner's
    # analytic model: a double-counted donation or dropped term in either
    # shows up as a gross disagreement
    from dlrover_tpu.parallel import planner

    spec = planner.model_spec_from_llama(config, 16)
    score = planner.estimate(
        MeshPlan(data=8, fsdp=1, seq=1, tensor=2), spec,
        planner.TPU_SPECS["v5p"], remat_policy="dots_saveable",
    )
    ratio = report.hbm_per_device_bytes / score.memory_bytes
    assert 0.3 < ratio < 3.0, (
        f"XLA-measured {report.hbm_per_device_bytes/1e9:.1f} GB vs "
        f"planner-modeled {score.memory_bytes/1e9:.1f} GB"
    )


def test_grouped_matmul_lowers_to_mosaic_deviceless():
    """The dropless-MoE grouped-matmul kernel — forward plus BOTH
    backward kernels (dx re-grouped GEMM, dw expert-accumulation with
    scalar-prefetch output indexing) — lowers to a real TPU executable
    hermetically at production-like shapes. Pins that the "grouped"
    dispatch is not an interpret-mode-only trick."""
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from dlrover_tpu.ops.grouped_matmul import grouped_matmul
    from dlrover_tpu.parallel.aot import _get_topology_desc_serialized

    topo = _get_topology_desc_serialized(topologies, "v5:2x2x1")
    dev = list(topo.devices)[:1]
    mesh = Mesh(np.array(dev).reshape(1), ("x",))
    repl = NamedSharding(mesh, P())

    e, d, f, bt = 8, 1024, 2816, 128
    tp = 16 * bt

    def loss(x, w, te):
        y = grouped_matmul(x, w, te, bt, 512, False)  # force Mosaic
        return (y ** 2).sum()

    g = jax.jit(jax.grad(loss, argnums=(0, 1)),
                in_shardings=(repl, repl, repl),
                out_shardings=(repl, repl))
    compiled = g.lower(
        jax.ShapeDtypeStruct((tp, d), jnp.bfloat16),
        jax.ShapeDtypeStruct((e, d, f), jnp.bfloat16),
        jax.ShapeDtypeStruct((tp // bt,), jnp.int32),
    ).compile()
    mem = compiled.memory_analysis()
    assert mem.temp_size_in_bytes > 0


def test_aot_lint_includes_concurrency_pass(monkeypatch):
    """`aot --lint` runs the DLR009-011 pass over the control plane and
    routes baseline-filtered findings into report.lint_findings (clean
    on HEAD; the injected finding pins the wiring)."""
    from dlrover_tpu.analysis import concurrency
    from dlrover_tpu.analysis.findings import Finding

    injected = Finding("DLR009", "fake/module.py", 7,
                       "rpc under a held lock", scope="C.m")
    monkeypatch.setattr(
        concurrency, "lint_paths_concurrency",
        lambda paths, root, rules=None, counters=None: [injected])
    config = llama.llama_tiny(use_flash=False)
    report = aot_compile_train_step(
        config, topology="v5p-16", tpu_gen="v5p", global_batch=16,
        model_name="llama_tiny", graph_lint=True,
    )
    assert report.lint_findings is not None
    dlr = [f for f in report.lint_findings
           if f.rule_id.startswith("DLR")]
    assert dlr == [injected]
    # and the serialized report carries it for the CLI exit path
    import json as _json

    data = _json.loads(report.to_json())
    assert any(e["rule"] == "DLR009"
               for e in data["lint_findings"])
