"""BERT and CLIP model families: shapes, gradients, training, sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models import bert, clip
from dlrover_tpu.parallel.accelerate import accelerate
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy


class TestBert:
    def test_forward_shapes(self):
        cfg = bert.bert_tiny()
        params = bert.init(jax.random.PRNGKey(0), cfg)
        ids = jnp.zeros((2, 16), jnp.int32)
        seq, pooled = bert.apply(params, ids, cfg)
        assert seq.shape == (2, 16, cfg.hidden_size)
        assert pooled.shape == (2, cfg.hidden_size)
        logits = bert.apply_mlm(params, ids, cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_attention_mask_changes_output(self):
        cfg = bert.bert_tiny()
        params = bert.init(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 8)))
        full, _ = bert.apply(params, ids, cfg)
        mask = jnp.asarray([[1, 1, 1, 1, 0, 0, 0, 0]] * 2)
        masked, _ = bert.apply(params, ids, cfg, attention_mask=mask)
        assert not np.allclose(np.asarray(full[:, 0]),
                               np.asarray(masked[:, 0]))

    # budget triage (PR 16): bert forward/masking are pinned by the
    # cheaper parity units; convergence representatives (llama/gpt2)
    # stay tier-1 — this overfit run rides slow
    @pytest.mark.slow
    def test_mlm_overfits_tiny_batch(self):
        cfg = bert.bert_tiny()
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 16)))
        labels = jnp.where(
            jnp.asarray(rng.rand(4, 16)) < 0.3, ids, -100
        )
        batch = {"input_ids": ids, "labels": labels}
        result = accelerate(
            bert.make_init_fn(cfg), bert.make_mlm_loss_fn(cfg),
            optax.adam(1e-3), batch,
            strategy=Strategy(mesh=MeshPlan(data=2, fsdp=2, tensor=2),
                              rule_set="bert"),
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        sb = result.shard_batch(batch)
        losses = []
        for i in range(15):
            state, m = result.train_step(state, sb, jax.random.PRNGKey(i))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0] * 0.7

    def test_param_count(self):
        assert bert.param_count(bert.bert_tiny()) > 0

    def test_bf16_compute_with_f32_params(self):
        # the production default: f32 params, bf16 compute — the scan
        # carry dtype must stay stable through the norms
        cfg = bert.bert_tiny(compute_dtype=jnp.bfloat16)
        params = bert.init(jax.random.PRNGKey(0), cfg)
        seq, _ = bert.apply(params, jnp.zeros((2, 8), jnp.int32), cfg)
        assert seq.dtype == jnp.bfloat16

    def test_clip_bf16_compute_with_f32_params(self):
        cfg = clip.clip_tiny(compute_dtype=jnp.bfloat16)
        params = clip.init(jax.random.PRNGKey(0), cfg)
        out = clip.encode_text(
            params, jnp.zeros((2, 8), jnp.int32), cfg
        )
        assert out.shape == (2, cfg.projection_dim)


class TestClip:
    def test_encoders_normalized(self):
        cfg = clip.clip_tiny()
        params = clip.init(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (3, 16)))
        pix = jnp.asarray(rng.rand(3, 32, 32, 3), jnp.float32)
        t = clip.encode_text(params, ids, cfg)
        v = clip.encode_image(params, pix, cfg)
        assert t.shape == (3, cfg.projection_dim)
        assert v.shape == (3, cfg.projection_dim)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(t), axis=-1), 1.0, rtol=1e-5
        )
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(v), axis=-1), 1.0, rtol=1e-5
        )

    def test_patchify_roundtrip_count(self):
        x = jnp.arange(2 * 32 * 32 * 3, dtype=jnp.float32).reshape(
            2, 32, 32, 3
        )
        patches = clip._patchify(x, 8)
        assert patches.shape == (2, 16, 8 * 8 * 3)

    @pytest.mark.slow  # PR 13 triage: an 11 s convergence loop — the
    # CLIP forward/loss contracts stay tier-1 via the encoder/metric
    # tests above and below
    def test_contrastive_training_aligns_pairs(self):
        cfg = clip.clip_tiny()
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)))
        pix = jnp.asarray(rng.rand(8, 32, 32, 3), jnp.float32)
        batch = {"input_ids": ids, "pixel_values": pix}
        result = accelerate(
            clip.make_init_fn(cfg), clip.make_loss_fn(cfg),
            optax.adam(3e-3), batch,
            strategy=Strategy(mesh=MeshPlan(data=2, fsdp=2, tensor=2),
                              rule_set="clip"),
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        sb = result.shard_batch(batch)
        losses = []
        for i in range(40):
            state, m = result.train_step(state, sb, jax.random.PRNGKey(i))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0] * 0.5

    def test_loss_metrics(self):
        cfg = clip.clip_tiny()
        params = clip.init(jax.random.PRNGKey(0), cfg)
        emb = jnp.eye(4, cfg.projection_dim)
        emb = emb / jnp.linalg.norm(emb, axis=-1, keepdims=True)
        loss, aux = clip.contrastive_loss(params, emb, emb)
        # identical aligned embeddings: accuracy 1
        assert float(aux["accuracy"]) == 1.0


class TestShardingRules:
    def test_bert_rules_bind_tensor_axis(self):
        from dlrover_tpu.parallel.sharding_rules import bert_rules

        mesh_sizes = {"fsdp": 2, "tensor": 2}
        rules = bert_rules()
        spec = rules.spec_for("layers/q_proj/kernel", (4, 32, 32),
                              mesh_sizes)
        assert spec == ("fsdp", None, "tensor")
        spec = rules.spec_for("embeddings/word/embedding", (128, 32),
                              mesh_sizes)
        assert spec == ("tensor", "fsdp")

    def test_clip_paths_bind_under_towers(self):
        from dlrover_tpu.parallel.sharding_rules import clip_rules

        spec = clip_rules().spec_for(
            "text/layers/q_proj/kernel", (2, 32, 32),
            {"fsdp": 2, "tensor": 2},
        )
        assert spec == ("fsdp", None, "tensor")


class TestBertPipelined:
    """BERT joins the pipelined families: the [B, S] attention mask
    rides the pipeline state beside its microbatch (GLM-prefix
    discipline), encoder blocks as GPipe/interleaved stages."""

    def test_pipelined_matches_apply_with_mask(self):
        cfg = bert.bert_tiny(num_layers=4)
        params = bert.init(jax.random.PRNGKey(0), cfg)
        ids = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 16))
        )
        # per-example masks differ so each microbatch carries its own
        mask = jnp.asarray(
            np.random.RandomState(1).randint(0, 2, (4, 16)).astype(np.int32)
        ).at[:, 0].set(1)
        seq, pooled = bert.apply(params, ids, cfg, attention_mask=mask)
        seq_p, pooled_p = bert.apply_pipelined(
            params, ids, cfg, num_stages=2, num_microbatches=2,
            attention_mask=mask,
        )
        np.testing.assert_allclose(np.asarray(seq_p), np.asarray(seq),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(pooled_p), np.asarray(pooled),
                                   rtol=2e-4, atol=2e-4)

    def test_uneven_interleaved_matches_apply(self):
        cfg = bert.bert_tiny(num_layers=6)
        params = bert.init(jax.random.PRNGKey(0), cfg)
        ids = jnp.asarray(
            np.random.RandomState(2).randint(0, cfg.vocab_size, (4, 16))
        )
        seq, _ = bert.apply(params, ids, cfg)
        seq_p, _ = bert.apply_pipelined(
            params, ids, cfg, num_stages=2, num_microbatches=2,
            num_virtual=2, stage_depths=(1, 2, 1, 2),
        )
        np.testing.assert_allclose(np.asarray(seq_p), np.asarray(seq),
                                   rtol=2e-4, atol=2e-4)

    # budget triage (PR 16): the pipeline engine is model-agnostic and
    # stays pinned tier-1 by the llama/neox/glm pp tests; bert's mask
    # plumbing by its apply-level parity — this trains run rides slow
    @pytest.mark.slow
    def test_trains_with_bert_pp_rules_on_mesh(self):
        from dlrover_tpu.models.losses import masked_lm_loss

        cfg = bert.bert_tiny(num_layers=4)

        def loss_fn(params, batch, rng):
            seq, _ = bert.apply_pipelined(
                params, batch["input_ids"], cfg,
                num_stages=2, num_microbatches=2,
            )
            logits = seq @ params["mlm_head"]["kernel"].astype(seq.dtype) \
                + params["mlm_head"]["bias"].astype(seq.dtype)
            return masked_lm_loss(logits.astype(jnp.float32),
                                  batch["labels"]), {}

        batch = {
            "input_ids": jax.random.randint(
                jax.random.PRNGKey(0), (8, 16), 0, cfg.vocab_size
            ),
            "labels": jax.random.randint(
                jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size
            ),
        }
        strategy = Strategy(
            mesh=MeshPlan(pipe=2, data=2, tensor=2), rule_set="bert_pp"
        )
        result = accelerate(
            bert.make_init_fn(cfg), loss_fn,
            optax.adam(1e-2), batch, strategy=strategy,
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        sharded = result.shard_batch(batch)
        losses = []
        for i in range(3):
            state, metrics = result.train_step(
                state, sharded, jax.random.PRNGKey(i)
            )
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]
