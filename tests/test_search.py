"""Strategy search: combination generation, GP/EI Bayesian loop,
strategy-info persistence, module replacement."""

import dataclasses

import numpy as np
import pytest

from dlrover_tpu.models import llama
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.module_replace import (
    apply_replacements,
    available_replacements,
)
from dlrover_tpu.parallel.search import (
    BayesianSearch,
    StrategyInfo,
    StrategyInfoCollection,
    bayesian_search_strategy,
    combination_candidates,
    encode_strategy,
)
from dlrover_tpu.parallel.strategy import Strategy


class TestCombinations:
    def test_covers_mesh_and_remat_space(self):
        cands = combination_candidates(8, max_candidates=1000)
        meshes = {tuple(dataclasses.astuple(c.mesh)) for c in cands}
        remats = {c.remat_policy for c in cands}
        assert len(meshes) > 1
        assert "" in remats and "dots_saveable" in remats

    def test_respects_global_batch_divisibility(self):
        base = Strategy(global_batch_size=4)
        cands = combination_candidates(8, base=base,
                                       accum_options=(1, 2, 3, 4))
        assert all(c.grad_accum_steps in (1, 2, 4) for c in cands)


class TestBayesianSearch:
    def _pool(self):
        return combination_candidates(
            8, remat_policies=["none", "dots_saveable"],
            accum_options=(1, 2), max_candidates=24,
        )

    def test_finds_synthetic_optimum(self):
        pool = self._pool()
        # synthetic objective: fastest when tensor axis is big and accum=1
        def cost(s):
            return (
                1.0 / max(s.mesh.tensor, 1)
                + 0.2 * s.grad_accum_steps
                + (0.1 if s.remat_policy else 0.0)
            )

        truth_best = min(pool, key=cost)
        search = BayesianSearch(pool, init_random=3)
        for _ in range(14):
            proposal = search.propose()
            if proposal is None:
                break
            idx, s = proposal
            search.observe(idx, cost(s))
        best, y = search.best
        assert y <= cost(truth_best) * 1.3

    def test_failed_candidates_excluded(self):
        pool = self._pool()[:4]
        search = BayesianSearch(pool, init_random=1)
        seen = set()
        for _ in range(10):
            p = search.propose()
            if p is None:
                break
            idx, _ = p
            assert idx not in seen
            seen.add(idx)
            search.observe(idx, 0.0, failed=True)
        assert search.propose() is None
        assert search.best is None

    def test_encode_distinguishes_strategies(self):
        a = encode_strategy(Strategy(mesh=MeshPlan(data=8)))
        b = encode_strategy(Strategy(mesh=MeshPlan(tensor=8)))
        assert not np.allclose(a, b)


class TestSearchLoop:
    def test_end_to_end_with_synthetic_evaluator(self):
        def evaluate(s):
            if s.mesh.pipe > 1:  # pretend pipe candidates OOM
                return StrategyInfo(strategy=s, error="OOM")
            t = 1.0 / max(s.mesh.data, 1) + 0.05 * s.grad_accum_steps
            return StrategyInfo(strategy=s, step_time_s=t)

        best, infos = bayesian_search_strategy(
            evaluate, n_devices=8, budget=10,
        )
        assert best.mesh.pipe == 1
        assert len(infos) == 10
        # persistence round-trip
        restored = StrategyInfoCollection.from_json(infos.to_json())
        assert restored.best.step_time_s == infos.best.step_time_s

    def test_raises_when_all_fail(self):
        with pytest.raises(RuntimeError):
            bayesian_search_strategy(
                lambda s: StrategyInfo(strategy=s, error="nope"),
                n_devices=8, budget=3,
            )


class TestModuleReplace:
    def test_flash_swap(self):
        cfg = llama.llama_tiny()
        assert not cfg.use_flash
        out = apply_replacements(cfg, "llama", ["flash_attention"])
        assert out.use_flash
        back = apply_replacements(out, "llama", ["reference_attention"])
        assert not back.use_flash

    def test_ring_attention_sets_seq_axis(self):
        cfg = llama.llama_tiny()
        out = apply_replacements(cfg, "llama", ["ring_attention"])
        assert out.seq_axis == "seq"

    def test_unknown_replacement_raises(self):
        with pytest.raises(ValueError):
            apply_replacements(llama.llama_tiny(), "llama", ["nope"])

    def test_catalog(self):
        assert "flash_attention" in available_replacements("llama")
        assert "ring_attention" not in available_replacements("gpt2")


class TestDryrunProcessSlice:
    """dryrun's per-process slice of the GLOBAL example batch must
    never silently drop trailing rows (the assembled global batch would
    stop matching strategy.global_batch_size)."""

    def test_even_rows_slice_cleanly(self):
        from dlrover_tpu.parallel.auto_tune import _process_local_slice

        batch = {"x": np.arange(12).reshape(6, 2)}
        for pid in range(3):
            out = _process_local_slice(batch, 3, pid)
            assert out["x"].shape == (2, 2)
            assert out["x"][0, 0] == pid * 4  # contiguous shares

    def test_indivisible_rows_raise(self):
        from dlrover_tpu.parallel.auto_tune import _process_local_slice

        batch = {"x": np.zeros((7, 2))}
        with pytest.raises(ValueError, match="not divisible"):
            _process_local_slice(batch, 3, 0)
