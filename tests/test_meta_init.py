"""Meta-init: abstract trees, stats, sharded/leafwise materialization."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models import llama
from dlrover_tpu.parallel.accelerate import accelerate
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.utils.meta_init import (
    abstract_init,
    default_leaf_init,
    materialize_leaf_by_leaf,
    materialize_sharded,
    param_stats,
)


def _init_fn(rng):
    k1, k2 = jax.random.split(rng)
    return {
        "w": jax.random.normal(k1, (16, 8)),
        "b": jnp.zeros((8,)),
        "emb": jax.random.normal(k2, (32, 16), jnp.bfloat16),
    }


class TestAbstractInit:
    def test_no_allocation_and_stats(self):
        abstract = abstract_init(_init_fn)
        assert abstract["w"].shape == (16, 8)
        stats = param_stats(abstract)
        assert stats["params"] == 16 * 8 + 8 + 32 * 16
        assert stats["bytes"] == (16 * 8 + 8) * 4 + 32 * 16 * 2

    def test_llama_param_count_matches(self):
        config = llama.llama_tiny()
        abstract = abstract_init(lambda r: llama.init(r, config))
        assert param_stats(abstract)["params"] == llama.param_count(config)


class TestMaterialize:
    def test_sharded_matches_plain_init(self):
        mesh = MeshPlan(data=-1).build()
        from jax.sharding import NamedSharding, PartitionSpec

        shardings = jax.tree.map(
            lambda _: NamedSharding(mesh, PartitionSpec()),
            abstract_init(_init_fn),
        )
        sharded = materialize_sharded(_init_fn, shardings)
        plain = _init_fn(jax.random.PRNGKey(0))
        np.testing.assert_allclose(
            np.asarray(sharded["w"]), np.asarray(plain["w"]), rtol=1e-6
        )

    def test_leaf_by_leaf_shapes_and_dtypes(self):
        abstract = abstract_init(_init_fn)
        tree = materialize_leaf_by_leaf(abstract, default_leaf_init)
        assert tree["w"].shape == (16, 8)
        assert tree["emb"].dtype == jnp.bfloat16
        assert float(jnp.abs(tree["w"]).sum()) > 0  # matrices randomized
        assert float(jnp.abs(tree["b"]).sum()) == 0  # vectors zeroed

    def test_leaf_by_leaf_with_shardings(self):
        mesh = MeshPlan(data=-1).build()
        from jax.sharding import NamedSharding, PartitionSpec

        abstract = abstract_init(_init_fn)
        shardings = jax.tree.map(
            lambda _: NamedSharding(mesh, PartitionSpec()), abstract
        )
        tree = materialize_leaf_by_leaf(
            abstract, default_leaf_init, shardings
        )
        assert tree["w"].sharding.mesh.shape  # placed on the mesh

    def test_leaf_count_mismatch_raises(self):
        abstract = abstract_init(_init_fn)
        with pytest.raises(ValueError):
            materialize_leaf_by_leaf(
                abstract, default_leaf_init, shardings=[1, 2]
            )


class TestAccelerateNeverMaterializesUnsharded:
    def test_init_goes_through_eval_shape(self):
        """accelerate's init path is jit(out_shardings=...): assert the
        state arrives already sharded on the mesh."""
        config = llama.llama_tiny()
        import numpy as np_

        ids = np_.random.RandomState(0).randint(0, config.vocab_size,
                                                (8, 17))
        batch = {"input_ids": jnp.asarray(ids[:, :-1]),
                 "labels": jnp.asarray(ids[:, 1:])}
        import optax

        result = accelerate(
            llama.make_init_fn(config), llama.make_loss_fn(config),
            optax.sgd(0.1), batch,
            strategy=Strategy(mesh=MeshPlan(data=2, fsdp=4),
                              rule_set="llama"),
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        emb = state.params["embed_tokens"]["embedding"]
        assert len(emb.sharding.mesh.devices.flatten()) == 8
