"""GPT-NeoX and GLM families: architecture semantics, gradients, sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models import glm, gpt_neox
from dlrover_tpu.parallel.accelerate import accelerate
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy


class TestGPTNeoX:
    def test_forward_shapes(self):
        cfg = gpt_neox.neox_tiny()
        params = gpt_neox.init(jax.random.PRNGKey(0), cfg)
        logits = gpt_neox.apply(params, jnp.zeros((2, 16), jnp.int32), cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_partial_rotary_dims(self):
        cfg = gpt_neox.neox_tiny()  # head_dim 16, pct 0.25
        assert cfg.rotary_dims == 4
        assert gpt_neox.neox_tiny(rotary_pct=1.0).rotary_dims == 16
        assert gpt_neox.neox_tiny(rotary_pct=0.0).rotary_dims == 0

    def test_rotary_gives_position_sensitivity(self):
        # one attention layer is permutation-invariant over its (key,
        # value) pairs, so WITHOUT any positional signal, permuting the
        # context leaves the last position's logits unchanged; rotary must
        # break that (multi-layer stacks lose the invariance through the
        # causal mask on intermediate positions, hence num_layers=1)
        ids = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
        perm = jnp.asarray([[4, 2, 3, 1, 6, 5, 7, 8]], jnp.int32)

        cfg_rot = gpt_neox.neox_tiny(num_layers=1)
        params = gpt_neox.init(jax.random.PRNGKey(0), cfg_rot)
        a = gpt_neox.apply(params, ids, cfg_rot)
        b = gpt_neox.apply(params, perm, cfg_rot)
        assert not np.allclose(np.asarray(a[0, -1]), np.asarray(b[0, -1]),
                               atol=1e-6)

        cfg_norot = gpt_neox.neox_tiny(num_layers=1, rotary_pct=0.0)
        a = gpt_neox.apply(params, ids, cfg_norot)
        b = gpt_neox.apply(params, perm, cfg_norot)
        np.testing.assert_allclose(np.asarray(a[0, -1]),
                                   np.asarray(b[0, -1]), rtol=1e-5,
                                   atol=1e-6)

    def test_parallel_vs_sequential_residual_differ(self):
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 256, (2, 8)))
        p_cfg = gpt_neox.neox_tiny(use_parallel_residual=True)
        s_cfg = gpt_neox.neox_tiny(use_parallel_residual=False)
        params = gpt_neox.init(jax.random.PRNGKey(0), p_cfg)
        out_p = gpt_neox.apply(params, ids, p_cfg)
        out_s = gpt_neox.apply(params, ids, s_cfg)
        assert not np.allclose(np.asarray(out_p), np.asarray(out_s))

    def test_packed_segments_equal_separate_documents(self):
        # both dispatch paths: bias reference and fused kernel
        for cfg in (gpt_neox.neox_tiny(),
                    gpt_neox.neox_tiny(use_flash=True,
                                       flash_interpret=True)):
            params = gpt_neox.init(jax.random.PRNGKey(0), cfg)
            rng = np.random.RandomState(0)
            doc_a = rng.randint(0, cfg.vocab_size, (1, 12))
            doc_b = rng.randint(0, cfg.vocab_size, (1, 20))
            packed_ids = jnp.asarray(
                np.concatenate([doc_a, doc_b], axis=1))
            seg = jnp.asarray([[0] * 12 + [1] * 20])
            packed = gpt_neox.apply(params, packed_ids, cfg,
                                    segment_ids=seg)
            alone_a = gpt_neox.apply(params, jnp.asarray(doc_a), cfg)
            alone_b = gpt_neox.apply(params, jnp.asarray(doc_b), cfg)
            np.testing.assert_allclose(packed[0, :12], alone_a[0],
                                       atol=2e-5, rtol=2e-5)
            np.testing.assert_allclose(packed[0, 12:], alone_b[0],
                                       atol=2e-5, rtol=2e-5)

    def test_seq_parallel_ring_matches_dense(self):
        """NeoX long-context: the model under a (data x seq) mesh with
        ring attention equals the dense model — including packed
        segments riding the ring (llama-branch semantics for the
        second decoder family)."""
        mesh = MeshPlan(data=2, seq=4).build()
        cfg_ring = gpt_neox.neox_tiny(remat_policy="none",
                                      seq_axis="seq", mesh=mesh)
        cfg_dense = gpt_neox.neox_tiny(remat_policy="none")
        params = gpt_neox.init(jax.random.PRNGKey(0), cfg_ring)
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, cfg_ring.vocab_size, (2, 64)))
        out_ring, _ = gpt_neox.apply(params, ids, cfg_ring)
        out_dense, _ = gpt_neox.apply(params, ids, cfg_dense)
        np.testing.assert_allclose(np.asarray(out_ring),
                                   np.asarray(out_dense),
                                   atol=3e-5, rtol=3e-5)
        # packed documents spanning ring shards
        seg = jnp.asarray(np.sort(rng.randint(0, 3, (2, 64)), axis=1))
        out_ring, _ = gpt_neox.apply(params, ids, cfg_ring,
                                     segment_ids=seg)
        out_dense, _ = gpt_neox.apply(params, ids, cfg_dense,
                                      segment_ids=seg)
        np.testing.assert_allclose(np.asarray(out_ring),
                                   np.asarray(out_dense),
                                   atol=3e-5, rtol=3e-5)

    # budget triage (PR 16): neox parity stays pinned tier-1 by
    # test_packed_segments_equal_separate_documents; the overfit
    # convergence run rides slow
    @pytest.mark.slow
    def test_overfits_tiny_batch_sharded(self):
        cfg = gpt_neox.neox_tiny()
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 16)))
        batch = {"input_ids": ids, "labels": ids}
        result = accelerate(
            gpt_neox.make_init_fn(cfg), gpt_neox.make_loss_fn(cfg),
            optax.adam(1e-3), batch,
            strategy=Strategy(mesh=MeshPlan(data=2, fsdp=2, tensor=2),
                              rule_set="neox"),
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        sb = result.shard_batch(batch)
        losses = []
        for i in range(15):
            state, m = result.train_step(state, sb, jax.random.PRNGKey(i))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0] * 0.7


class TestGLM:
    def test_prefix_lm_seq_parallel_ring_matches_dense(self):
        """GLM long context: the prefix-LM model under a (data x seq)
        mesh — the prefix mask decomposed over the ring — equals the
        dense prefix model, prefixes straddling ring-shard bounds.
        The causal and packed GLM modes ride the same branch."""
        mesh = MeshPlan(data=2, seq=4).build()
        cfg_ring = glm.glm_tiny(remat_policy="none", seq_axis="seq",
                                mesh=mesh)
        cfg_dense = glm.glm_tiny(remat_policy="none")
        params = glm.init(jax.random.PRNGKey(0), cfg_ring)
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, cfg_ring.vocab_size, (2, 64)))
        prefix = jnp.asarray([23, 50], jnp.int32)  # shard size is 16
        out_ring = glm.apply(params, ids, cfg_ring, prefix_len=prefix)
        out_dense = glm.apply(params, ids, cfg_dense,
                              prefix_len=prefix)
        np.testing.assert_allclose(np.asarray(out_ring),
                                   np.asarray(out_dense),
                                   atol=3e-5, rtol=3e-5)
        # causal mode through the same ring branch
        out_ring = glm.apply(params, ids, cfg_ring)
        out_dense = glm.apply(params, ids, cfg_dense)
        np.testing.assert_allclose(np.asarray(out_ring),
                                   np.asarray(out_dense),
                                   atol=3e-5, rtol=3e-5)
        # packed mode (segment ids ride the ring)
        seg = jnp.asarray(np.sort(rng.randint(0, 3, (2, 64)), axis=1))
        out_ring = glm.apply(params, ids, cfg_ring, segment_ids=seg)
        out_dense = glm.apply(params, ids, cfg_dense, segment_ids=seg)
        np.testing.assert_allclose(np.asarray(out_ring),
                                   np.asarray(out_dense),
                                   atol=3e-5, rtol=3e-5)

    def test_forward_shapes_causal(self):
        cfg = glm.glm_tiny()
        params = glm.init(jax.random.PRNGKey(0), cfg)
        logits = glm.apply(params, jnp.zeros((2, 16), jnp.int32), cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_glm_positions(self):
        pos, block = glm.glm_positions(6, jnp.asarray([3, 0]))
        np.testing.assert_array_equal(
            np.asarray(pos), [[0, 1, 2, 3, 3, 3], [0, 0, 0, 0, 0, 0]])
        np.testing.assert_array_equal(
            np.asarray(block), [[0, 0, 0, 1, 2, 3], [1, 2, 3, 4, 5, 6]])

    def test_prefix_lm_bias_matches_bruteforce(self):
        s, p = 5, 3
        bias = np.asarray(glm.prefix_lm_bias(s, jnp.asarray([p])))[0, 0]
        for i in range(s):
            for j in range(s):
                allowed = (j < p) or (j <= i)
                assert (bias[i, j] == 0.0) == allowed, (i, j)

    def test_prefix_is_bidirectional_causal_tail_is_not(self):
        cfg = glm.glm_tiny()
        params = glm.init(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (1, 8)))
        ids2 = ids.at[0, 2].set((ids[0, 2] + 1) % cfg.vocab_size)

        # prefix_len=4: editing token 2 (inside the prefix) must change
        # position 0's output — the prefix attends bidirectionally
        p4 = jnp.asarray([4])
        out_a = glm.apply(params, ids, cfg, prefix_len=p4)
        out_b = glm.apply(params, ids2, cfg, prefix_len=p4)
        assert not np.allclose(np.asarray(out_a[0, 0]),
                               np.asarray(out_b[0, 0]), atol=1e-6)

        # editing token 6 (in the causal tail) must NOT change position 0
        ids3 = ids.at[0, 6].set((ids[0, 6] + 1) % cfg.vocab_size)
        out_c = glm.apply(params, ids3, cfg, prefix_len=p4)
        np.testing.assert_allclose(np.asarray(out_a[0, 0]),
                                   np.asarray(out_c[0, 0]), rtol=1e-5)

    def test_zero_prefix_is_strictly_causal(self):
        # prefix_len=0 uses GLM's generation-span positions (pos frozen at
        # 0, block positions 1..S — intentionally NOT the same encoding as
        # prefix_len=None plain causal LM) but the mask must be strictly
        # causal: editing a later token cannot change an earlier position
        cfg = glm.glm_tiny()
        params = glm.init(jax.random.PRNGKey(0), cfg)
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 256, (1, 8)))
        ids2 = ids.at[0, 6].set((ids[0, 6] + 1) % cfg.vocab_size)
        zero = jnp.zeros((1,), jnp.int32)
        out_a = glm.apply(params, ids, cfg, prefix_len=zero)
        out_b = glm.apply(params, ids2, cfg, prefix_len=zero)
        np.testing.assert_allclose(np.asarray(out_a[0, :6]),
                                   np.asarray(out_b[0, :6]), rtol=1e-5)

    # budget triage (PR 16): GLM's prefix behavior stays pinned tier-1
    # by the ring-vs-dense and packed-segments parities; the overfit
    # convergence run rides slow
    @pytest.mark.slow
    def test_overfits_prefix_batch_sharded(self):
        cfg = glm.glm_tiny()
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (4, 16)))
        prefix = jnp.asarray([4, 4, 4, 4], jnp.int32)
        # loss only over the generation span (HF -100 convention)
        mask = jnp.arange(16)[None, :] >= prefix[:, None]
        labels = jnp.where(mask, ids, -100)
        batch = {"input_ids": ids, "labels": labels, "prefix_len": prefix}
        result = accelerate(
            glm.make_init_fn(cfg), glm.make_loss_fn(cfg),
            optax.adam(1e-3), batch,
            strategy=Strategy(mesh=MeshPlan(data=2, fsdp=2, tensor=2),
                              rule_set="glm"),
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        sb = result.shard_batch(batch)
        losses = []
        for i in range(15):
            state, m = result.train_step(state, sb, jax.random.PRNGKey(i))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0] * 0.7

    def test_packed_segments_equal_separate_documents(self):
        # BOTH dispatch paths: the bias reference (use_flash=False) and
        # the fused kernel (use_flash=True, interpret) — the flash
        # branch ordering silently dropping the mask is the regression
        # this guards
        for cfg in (glm.glm_tiny(),
                    glm.glm_tiny(use_flash=True, flash_interpret=True)):
            params = glm.init(jax.random.PRNGKey(0), cfg)
            rng = np.random.RandomState(0)
            doc_a = rng.randint(0, cfg.vocab_size, (1, 14))
            doc_b = rng.randint(0, cfg.vocab_size, (1, 18))
            packed_ids = jnp.asarray(
                np.concatenate([doc_a, doc_b], axis=1))
            seg = jnp.asarray([[0] * 14 + [1] * 18])
            packed = glm.apply(params, packed_ids, cfg, segment_ids=seg)
            alone_a = glm.apply(params, jnp.asarray(doc_a), cfg)
            alone_b = glm.apply(params, jnp.asarray(doc_b), cfg)
            np.testing.assert_allclose(packed[0, :14], alone_a[0],
                                       atol=2e-5, rtol=2e-5)
            np.testing.assert_allclose(packed[0, 14:], alone_b[0],
                                       atol=2e-5, rtol=2e-5)

    def test_prefix_and_segments_mutually_exclusive(self):
        cfg = glm.glm_tiny()
        params = glm.init(jax.random.PRNGKey(0), cfg)
        ids = jnp.zeros((1, 8), jnp.int32)
        with pytest.raises(ValueError, match="mutually exclusive"):
            glm.apply(params, ids, cfg,
                      prefix_len=jnp.asarray([2]),
                      segment_ids=jnp.zeros((1, 8), jnp.int32))

    def test_param_counts(self):
        assert glm.param_count(glm.glm_tiny()) > 0
        assert gpt_neox.param_count(gpt_neox.neox_tiny()) > 0


class TestNeoXGLMPipelined:
    """Pipeline parallelism for the NeoX/GLM families — same GPipe /
    interleaved / uneven-depth formulation as llama's, with GLM's
    prefix-LM mask context riding the pipeline state beside its
    microbatch."""

    def test_neox_pipelined_matches_apply(self):
        cfg = gpt_neox.neox_tiny(num_layers=4)
        params = gpt_neox.init(jax.random.PRNGKey(0), cfg)
        ids = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 16))
        )
        plain = gpt_neox.apply(params, ids, cfg)
        piped = gpt_neox.apply_pipelined(
            params, ids, cfg, num_stages=2, num_microbatches=2
        )
        np.testing.assert_allclose(np.asarray(piped), np.asarray(plain),
                                   rtol=2e-4, atol=2e-4)

    def test_neox_interleaved_uneven_matches_apply(self):
        cfg = gpt_neox.neox_tiny(num_layers=6)
        params = gpt_neox.init(jax.random.PRNGKey(0), cfg)
        ids = jnp.asarray(
            np.random.RandomState(1).randint(0, cfg.vocab_size, (4, 16))
        )
        plain = gpt_neox.apply(params, ids, cfg)
        piped = gpt_neox.apply_pipelined(
            params, ids, cfg, num_stages=2, num_microbatches=2,
            num_virtual=2, stage_depths=(1, 2, 1, 2),
        )
        np.testing.assert_allclose(np.asarray(piped), np.asarray(plain),
                                   rtol=2e-4, atol=2e-4)

    def test_neox_trains_with_pp_rules_on_mesh(self):
        from dlrover_tpu.models.losses import masked_lm_loss

        cfg = gpt_neox.neox_tiny(num_layers=4)

        def loss_fn(params, batch, rng):
            logits = gpt_neox.apply_pipelined(
                params, batch["input_ids"], cfg,
                num_stages=2, num_microbatches=2,
            )
            return masked_lm_loss(logits, batch["labels"]), {}

        batch = {
            "input_ids": jax.random.randint(
                jax.random.PRNGKey(0), (8, 16), 0, cfg.vocab_size
            ),
            "labels": jax.random.randint(
                jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size
            ),
        }
        strategy = Strategy(
            mesh=MeshPlan(pipe=2, data=2, tensor=2), rule_set="neox_pp"
        )
        result = accelerate(
            gpt_neox.make_init_fn(cfg), loss_fn,
            optax.adam(1e-2), batch, strategy=strategy,
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        sharded = result.shard_batch(batch)
        losses = []
        for i in range(3):
            state, metrics = result.train_step(
                state, sharded, jax.random.PRNGKey(i)
            )
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]

    def test_glm_pipelined_causal_matches_apply(self):
        cfg = glm.glm_tiny(num_layers=4)
        params = glm.init(jax.random.PRNGKey(0), cfg)
        ids = jnp.asarray(
            np.random.RandomState(2).randint(0, cfg.vocab_size, (4, 16))
        )
        plain = glm.apply(params, ids, cfg)
        piped = glm.apply_pipelined(
            params, ids, cfg, num_stages=2, num_microbatches=2
        )
        np.testing.assert_allclose(np.asarray(piped), np.asarray(plain),
                                   rtol=2e-4, atol=2e-4)

    def test_glm_pipelined_prefix_matches_apply(self):
        """The prefix mask must ride the ring WITH its microbatch:
        per-example prefix lengths differ across microbatches, so a
        stage sees a different mask every tick."""
        for use_flash in (False, True):
            cfg = glm.glm_tiny(num_layers=4, use_flash=use_flash,
                               flash_interpret=use_flash)
            params = glm.init(jax.random.PRNGKey(0), cfg)
            ids = jnp.asarray(
                np.random.RandomState(3).randint(0, cfg.vocab_size, (4, 16))
            )
            prefix = jnp.asarray([3, 7, 0, 5], jnp.int32)
            plain = glm.apply(params, ids, cfg, prefix_len=prefix)
            piped = glm.apply_pipelined(
                params, ids, cfg, num_stages=2, num_microbatches=2,
                prefix_len=prefix,
            )
            np.testing.assert_allclose(
                np.asarray(piped), np.asarray(plain), rtol=2e-4, atol=2e-4
            )

    def test_glm_pipelined_prefix_uneven_interleaved(self):
        # both mask paths: dense additive bias AND the Pallas prefix
        # kernel — the fused kernel must stay numerically inert on the
        # zero-padded masked slots of an uneven chunk
        for use_flash in (False, True):
            cfg = glm.glm_tiny(num_layers=6, use_flash=use_flash,
                               flash_interpret=use_flash)
            params = glm.init(jax.random.PRNGKey(0), cfg)
            ids = jnp.asarray(
                np.random.RandomState(4).randint(0, cfg.vocab_size, (4, 16))
            )
            prefix = jnp.asarray([2, 9, 4, 0], jnp.int32)
            plain = glm.apply(params, ids, cfg, prefix_len=prefix)
            piped = glm.apply_pipelined(
                params, ids, cfg, num_stages=2, num_microbatches=2,
                prefix_len=prefix, num_virtual=2,
                stage_depths=(2, 1, 2, 1),
            )
            np.testing.assert_allclose(
                np.asarray(piped), np.asarray(plain), rtol=2e-4, atol=2e-4
            )
