"""Cost-model planner: analytic mesh scoring, stage splitting,
device preloader."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.planner import (
    DeviceSpec,
    ModelSpec,
    estimate,
    plan_mesh,
    plan_stages,
)
from dlrover_tpu.trainer.data import DevicePreloader


def _llama7b_spec(batch=64):
    return ModelSpec(
        param_count=7_000_000_000, num_layers=32, hidden_size=4096,
        seq_len=4096, global_batch=batch, vocab_size=32000,
    )


class TestEstimate:
    def test_pure_dp_oom_for_7b_on_v5e(self):
        # 7B params * 10B/param optimizer footprint >> 16GB: data-only
        # replication cannot fit
        score = estimate(MeshPlan(data=8), _llama7b_spec())
        assert not score.fits

    def test_sharding_params_fits(self):
        score = estimate(
            MeshPlan(fsdp=16, tensor=4), _llama7b_spec(),
            DeviceSpec(hbm_bytes=95e9),  # v5p
        )
        assert score.fits
        assert score.step_time_s > 0

    def test_tp_comm_grows_with_tensor_axis(self):
        spec = _llama7b_spec()
        t4 = estimate(MeshPlan(fsdp=8, tensor=4), spec)
        t8 = estimate(MeshPlan(fsdp=4, tensor=8), spec)
        assert t8.breakdown["tp_comm_s"] > t4.breakdown["tp_comm_s"]

    def test_more_chips_less_compute_time(self):
        spec = _llama7b_spec()
        small = estimate(MeshPlan(fsdp=8), spec)
        big = estimate(MeshPlan(fsdp=32), spec)
        assert big.breakdown["compute_s"] < small.breakdown["compute_s"]


class TestPlanMesh:
    def test_picks_feasible_fastest(self):
        # v5e (16GB): a 7B model + optimizer (~70GB) must be sharded at
        # least 8-way across fsdp/tensor/pipe to fit
        scores = plan_mesh(_llama7b_spec(), n_devices=32, top_k=3)
        assert len(scores) == 3
        assert scores[0].step_time_s <= scores[1].step_time_s
        assert all(s.fits for s in scores)
        best = scores[0].plan
        assert best.fsdp * best.tensor * best.pipe >= 8

    def test_big_hbm_allows_pure_dp(self):
        # v5p (95GB) holds the whole replica: pure DP is feasible and,
        # with zero comm-heavy sharding, wins the analytic ranking
        scores = plan_mesh(
            _llama7b_spec(), n_devices=32,
            device=DeviceSpec(hbm_bytes=95e9), top_k=1,
        )
        assert scores[0].fits

    def test_degrades_when_nothing_fits(self):
        scores = plan_mesh(
            _llama7b_spec(), n_devices=2,
            device=DeviceSpec(hbm_bytes=16e9),
        )
        assert len(scores) == 1  # least-bad plan still returned


class TestPlanStages:
    def test_balances_uniform_layers(self):
        spans = plan_stages([1.0] * 8, 4)
        assert spans == [(0, 2), (2, 4), (4, 6), (6, 8)]

    def test_respects_heavy_layer(self):
        # one layer dominating: it gets its own stage
        costs = [1, 1, 1, 10, 1, 1]
        spans = plan_stages(costs, 3)
        maxes = [sum(costs[a:b]) for a, b in spans]
        assert max(maxes) == 10
        # contiguous, covering
        assert spans[0][0] == 0 and spans[-1][1] == len(costs)
        for (a, b), (c, d) in zip(spans, spans[1:]):
            assert b == c

    def test_rejects_bad_split(self):
        with pytest.raises(ValueError):
            plan_stages([1.0, 2.0], 3)


class TestDevicePreloader:
    def test_yields_all_batches_in_order(self):
        batches = [{"x": np.full((2,), i)} for i in range(5)]
        out = list(DevicePreloader(batches, prefetch=2))
        assert len(out) == 5
        for i, b in enumerate(out):
            assert isinstance(b["x"], jax.Array)
            assert int(b["x"][0]) == i

    def test_with_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = MeshPlan(data=-1).build()
        sharding = NamedSharding(mesh, PartitionSpec())
        out = list(DevicePreloader(
            [{"x": np.arange(4)}], sharding=sharding
        ))
        assert out[0]["x"].sharding == sharding

    def test_short_iterable(self):
        out = list(DevicePreloader([{"x": np.zeros(1)}], prefetch=4))
        assert len(out) == 1

    def test_invalid_prefetch(self):
        with pytest.raises(ValueError):
            DevicePreloader([], prefetch=0)
