"""Cost-model planner: analytic mesh scoring, stage splitting,
device preloader."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.planner import (
    MEASURED_ANCHORS,
    TPU_SPECS,
    DeviceSpec,
    ModelSpec,
    calibrated_efficiency,
    estimate,
    plan_mesh,
    plan_stages,
    ring_kv_repeat,
)
from dlrover_tpu.trainer.data import DevicePreloader


def _llama7b_spec(batch=64):
    return ModelSpec(
        param_count=7_000_000_000, num_layers=32, hidden_size=4096,
        seq_len=4096, global_batch=batch, vocab_size=32000,
    )


class TestEstimate:
    def test_pure_dp_oom_for_7b_on_v5e(self):
        # 7B params * 10B/param optimizer footprint >> 16GB: data-only
        # replication cannot fit
        score = estimate(MeshPlan(data=8), _llama7b_spec())
        assert not score.fits

    def test_sharding_params_fits(self):
        score = estimate(
            MeshPlan(fsdp=16, tensor=4), _llama7b_spec(),
            DeviceSpec(hbm_bytes=95e9),  # v5p
        )
        assert score.fits
        assert score.step_time_s > 0

    def test_tp_comm_grows_with_tensor_axis(self):
        spec = _llama7b_spec()
        t4 = estimate(MeshPlan(fsdp=8, tensor=4), spec)
        t8 = estimate(MeshPlan(fsdp=4, tensor=8), spec)
        assert t8.breakdown["tp_comm_s"] > t4.breakdown["tp_comm_s"]

    def test_more_chips_less_compute_time(self):
        spec = _llama7b_spec()
        small = estimate(MeshPlan(fsdp=8), spec)
        big = estimate(MeshPlan(fsdp=32), spec)
        assert big.breakdown["compute_s"] < small.breakdown["compute_s"]


class TestCalibration:
    """The cost model must reproduce the measured BENCH anchors and never
    emit unphysical numbers (round-2 verdict weak #1: AOT_7B.json claimed
    predicted_mfu=1.31)."""

    def test_efficiency_is_physical(self):
        eff = calibrated_efficiency()
        assert 0.3 < eff < 0.9

    @pytest.mark.parametrize("anchor", MEASURED_ANCHORS,
                             ids=lambda a: a.name)
    def test_predicts_anchor_step_time_within_25pct(self, anchor):
        score = estimate(
            MeshPlan(data=1, fsdp=1, seq=1, tensor=1),
            anchor.model,
            TPU_SPECS[anchor.device_gen],
            remat_policy=anchor.remat_policy,
        )
        rel = abs(score.step_time_s - anchor.measured_step_s)
        assert rel / anchor.measured_step_s < 0.25, (
            f"{anchor.name}: predicted {score.step_time_s:.3f}s vs "
            f"measured {anchor.measured_step_s:.3f}s"
        )
        assert abs(score.predicted_mfu - anchor.measured_mfu) < 0.25 * (
            anchor.measured_mfu
        )

    def test_predicted_mfu_always_below_one(self):
        # even a zero-comm single-chip plan with no remat must stay
        # physical: efficiency is clamped to 0.9
        spec = _llama7b_spec(batch=1024)
        for plan in (MeshPlan(data=1, fsdp=1), MeshPlan(fsdp=64),
                     MeshPlan(data=8, tensor=8)):
            for remat in ("", "dots_saveable", "full"):
                s = estimate(plan, spec, DeviceSpec(hbm_bytes=95e9),
                             remat_policy=remat)
                assert 0.0 < s.predicted_mfu < 1.0

    def test_pipe_activation_handoff_priced_on_dcn(self):
        spec = _llama7b_spec()
        piped = estimate(MeshPlan(pipe=4, fsdp=8), spec)
        flat = estimate(MeshPlan(fsdp=32), spec)
        assert piped.breakdown["pipe_comm_s"] > 0
        assert flat.breakdown["pipe_comm_s"] == 0

    def test_remat_recompute_slows_prediction(self):
        spec = _llama7b_spec()
        none = estimate(MeshPlan(fsdp=16), spec)
        full = estimate(MeshPlan(fsdp=16), spec, remat_policy="full")
        assert full.breakdown["compute_s"] > none.breakdown["compute_s"]


class TestRingKvRepeat:
    def test_divisible_no_repeat(self):
        assert ring_kv_repeat(8, 32, 4) == 1

    def test_indivisible_minimal_repeat(self):
        # 8 kv heads over tensor=16 -> repeat x2 (16 kv heads)
        assert ring_kv_repeat(8, 32, 16) == 2

    def test_unshardable_heads_match_runtime_and_demote_plan(self):
        """When no legal repeat exists the runtime legalizer raises; the
        planner must agree (None) and mark any such mesh infeasible —
        otherwise the search can select a program that cannot be
        built."""
        import pytest as _pytest

        from dlrover_tpu.ops.flash_attention import minimal_kv_repeat

        assert ring_kv_repeat(3, 6, 4) is None
        with _pytest.raises(ValueError):
            minimal_kv_repeat(3, 6, 4)

        spec = ModelSpec(
            param_count=int(1e8), num_layers=4, hidden_size=512,
            seq_len=256, global_batch=8, vocab_size=1024,
            num_heads=6, kv_heads=3,
        )
        score = estimate(MeshPlan(data=2, tensor=4), spec)
        assert not score.fits
        assert score.step_time_s == float("inf")
        # a legal head split on the same model stays feasible-rankable
        ok = estimate(MeshPlan(data=4, tensor=2), spec)
        assert ok.step_time_s != float("inf")

    def test_seq_comm_prices_the_repeat(self):
        # divisibility is a property of (kv_heads, tensor): the same GQA
        # model pays 2x the ring bytes when tensor=16 forces kv repeat
        spec = ModelSpec(param_count=7e9, num_layers=32, hidden_size=4096,
                         seq_len=8192, global_batch=16,
                         num_heads=32, kv_heads=8)
        ok = estimate(MeshPlan(fsdp=2, seq=2, tensor=4), spec)
        costly = estimate(MeshPlan(fsdp=2, seq=2, tensor=16), spec)
        assert costly.breakdown["seq_comm_s"] > ok.breakdown["seq_comm_s"]


class TestPlanMesh:
    def test_picks_feasible_fastest(self):
        # v5e (16GB): a 7B model + optimizer (~70GB) must be sharded at
        # least 8-way across fsdp/tensor/pipe to fit
        scores = plan_mesh(_llama7b_spec(batch=16), n_devices=32, top_k=3)
        assert len(scores) == 3
        assert scores[0].step_time_s <= scores[1].step_time_s
        assert all(s.fits for s in scores)
        best = scores[0].plan
        assert best.fsdp * best.tensor * best.pipe >= 8

    def test_big_hbm_allows_pure_dp(self):
        # v5p (95GB) holds the whole replica: pure DP is feasible and,
        # with zero comm-heavy sharding, wins the analytic ranking
        scores = plan_mesh(
            _llama7b_spec(), n_devices=32,
            device=DeviceSpec(hbm_bytes=95e9), top_k=1,
        )
        assert scores[0].fits

    def test_degrades_when_nothing_fits(self):
        scores = plan_mesh(
            _llama7b_spec(), n_devices=2,
            device=DeviceSpec(hbm_bytes=16e9),
        )
        assert len(scores) == 1  # least-bad plan still returned


class TestPlanStages:
    def test_balances_uniform_layers(self):
        spans = plan_stages([1.0] * 8, 4)
        assert spans == [(0, 2), (2, 4), (4, 6), (6, 8)]

    def test_respects_heavy_layer(self):
        # one layer dominating: it gets its own stage
        costs = [1, 1, 1, 10, 1, 1]
        spans = plan_stages(costs, 3)
        maxes = [sum(costs[a:b]) for a, b in spans]
        assert max(maxes) == 10
        # contiguous, covering
        assert spans[0][0] == 0 and spans[-1][1] == len(costs)
        for (a, b), (c, d) in zip(spans, spans[1:]):
            assert b == c

    def test_rejects_bad_split(self):
        with pytest.raises(ValueError):
            plan_stages([1.0, 2.0], 3)


@pytest.mark.slow
class TestPlannerRankingVsMeasured:
    """The analytic ranking must agree with measured dryrun ordering on
    the 8-device CPU mesh (round-2 verdict #1 'done' criterion): the
    planner is only useful if its argmin matches what a real timed
    dryrun would have picked."""

    CANDIDATES = [
        MeshPlan(data=8, fsdp=1, seq=1, tensor=1),
        MeshPlan(data=2, fsdp=1, seq=1, tensor=4),
        MeshPlan(data=1, fsdp=1, seq=1, tensor=8),
    ]

    def test_ranking_matches_dryrun(self):
        import optax

        from dlrover_tpu.models import llama
        from dlrover_tpu.parallel.accelerate import accelerate
        from dlrover_tpu.parallel.auto_tune import dryrun
        from dlrover_tpu.parallel.planner import model_spec_from_llama
        from dlrover_tpu.parallel.strategy import Strategy

        config = llama.llama_tiny(
            hidden_size=128, intermediate_size=256, num_heads=8,
            num_kv_heads=8, num_layers=2, max_seq_len=128,
        )
        batch_rows = 32
        rng = np.random.RandomState(0)
        ids = rng.randint(0, config.vocab_size, size=(batch_rows, 129))
        batch = {
            "input_ids": jnp.asarray(ids[:, :-1]),
            "labels": jnp.asarray(ids[:, 1:]),
        }

        def measure_all():
            out = []
            for plan in self.CANDIDATES:
                result = accelerate(
                    llama.make_init_fn(config),
                    llama.make_loss_fn(config),
                    optax.sgd(1e-3),
                    batch,
                    strategy=Strategy(mesh=plan, rule_set="llama"),
                )
                report = dryrun(result, batch, warmup_steps=2,
                                profile_steps=10)
                assert report.ok, report.error
                out.append(report.step_time_s)
            return out

        spec = model_spec_from_llama(config, batch_rows)
        predicted = [estimate(p, spec).step_time_s
                     for p in self.CANDIDATES]

        # the planner's contract is picking the winner (argmin), not a
        # total order of near-ties; wall-clock on a shared 1-core host is
        # noisy, so allow one re-measure before declaring disagreement
        measured = measure_all()
        if int(np.argmin(measured)) != int(np.argmin(predicted)):
            measured = measure_all()
        assert int(np.argmin(measured)) == int(np.argmin(predicted)), (
            f"planner ranking {predicted} disagrees with measured "
            f"{measured}"
        )


class TestDevicePreloader:
    def test_yields_all_batches_in_order(self):
        batches = [{"x": np.full((2,), i)} for i in range(5)]
        out = list(DevicePreloader(batches, prefetch=2))
        assert len(out) == 5
        for i, b in enumerate(out):
            assert isinstance(b["x"], jax.Array)
            assert int(b["x"][0]) == i

    def test_with_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = MeshPlan(data=-1).build()
        sharding = NamedSharding(mesh, PartitionSpec())
        out = list(DevicePreloader(
            [{"x": np.arange(4)}], sharding=sharding
        ))
        assert out[0]["x"].sharding == sharding

    def test_short_iterable(self):
        out = list(DevicePreloader([{"x": np.zeros(1)}], prefetch=4))
        assert len(out) == 1

    def test_invalid_prefetch(self):
        with pytest.raises(ValueError):
            DevicePreloader([], prefetch=0)

    def test_steps_per_call_stacks_k_batches(self):
        # 5 batches at K=2 -> two stacked [2, ...] items, trailing
        # partial group dropped (fixed shapes only)
        batches = [{"x": np.full((4, 3), i)} for i in range(5)]
        out = list(DevicePreloader(batches, steps_per_call=2))
        assert len(out) == 2
        assert out[0]["x"].shape == (2, 4, 3)
        assert int(out[1]["x"][1][0, 0]) == 3

    def test_steps_per_call_with_stacked_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = MeshPlan(data=-1).build()
        sharding = NamedSharding(mesh, PartitionSpec(None, "data"))
        n = mesh.devices.size
        out = list(DevicePreloader(
            [{"x": np.zeros((n, 3))} for _ in range(2)],
            sharding=sharding, steps_per_call=2,
        ))
        assert out[0]["x"].shape == (2, n, 3)
        assert out[0]["x"].sharding == sharding

    def test_background_mode_yields_all_and_surfaces_errors(self):
        # the consolidated prefetcher's shm-path mode: background
        # thread + bounded queue, errors re-raised in the consumer
        out = list(DevicePreloader(
            iter(range(10)), put_fn=lambda x: x * 2, background=True,
        ))
        assert out == [x * 2 for x in range(10)]

        def boom():
            yield 1
            raise RuntimeError("producer died")

        it = iter(DevicePreloader(
            boom(), put_fn=lambda x: x, background=True,
        ))
        assert next(it) == 1
        with pytest.raises(RuntimeError, match="producer died"):
            list(it)

    def test_shm_device_prefetcher_is_the_same_implementation(self):
        from dlrover_tpu.trainer.shm_dataloader import DevicePrefetcher

        assert issubclass(DevicePrefetcher, DevicePreloader)
        out = list(DevicePrefetcher(iter(range(4)), lambda x: x + 1))
        assert out == [1, 2, 3, 4]


class TestDispatchOverheadTerm:
    """estimate() prices the host dispatch floor, amortized by
    steps_per_call (ISSUE 3: the planner knows why multi-step fusion
    helps tiny/fast steps and why big models don't care)."""

    def _tiny_model(self):
        return ModelSpec(
            param_count=1_000_000, num_layers=2, hidden_size=64,
            seq_len=128, global_batch=8,
        )

    def test_tiny_model_is_dispatch_bound_and_k_amortizes(self):
        from dlrover_tpu.parallel.planner import (
            HOST_DISPATCH_OVERHEAD_S,
            estimate,
        )

        plan = MeshPlan(data=1)
        a = estimate(plan, self._tiny_model())
        b = estimate(plan, self._tiny_model(), steps_per_call=8)
        assert a.breakdown["dispatch_s"] == pytest.approx(
            HOST_DISPATCH_OVERHEAD_S)
        assert b.breakdown["dispatch_s"] == pytest.approx(
            HOST_DISPATCH_OVERHEAD_S / 8)
        # floor-bound (plus the 1% device-time ranking residual)
        assert HOST_DISPATCH_OVERHEAD_S <= a.step_time_s \
            <= 1.1 * HOST_DISPATCH_OVERHEAD_S
        assert b.step_time_s < a.step_time_s

    def test_dispatch_floor_preserves_plan_ranking(self):
        # every tiny-model mesh hits the same host floor; the ranking
        # must still order by device time, not collapse into a tie
        from dlrover_tpu.parallel.planner import estimate

        spec = self._tiny_model()
        times = [
            estimate(p, spec).step_time_s
            for p in (MeshPlan(tensor=8), MeshPlan(data=2, tensor=4),
                      MeshPlan(data=8))
        ]
        assert len(set(times)) == len(times)

    def test_compute_bound_model_sees_a_floor_not_a_tax(self):
        from dlrover_tpu.parallel.planner import estimate

        model = ModelSpec(
            param_count=7_000_000_000, num_layers=32, hidden_size=4096,
            seq_len=4096, global_batch=64,
        )
        plan = MeshPlan(data=2, fsdp=4)
        a = estimate(plan, model)
        b = estimate(plan, model, steps_per_call=8)
        # a 7B step is orders of magnitude above the dispatch floor:
        # fusing steps must not change its predicted time at all
        assert a.step_time_s == b.step_time_s
        assert a.step_time_s > 100 * a.breakdown["dispatch_s"]


class TestPlanStageDepths:
    """plan_stage_depths bridges the stage-split DP to
    Strategy.stage_depths (reference base_stage_planner.py:125)."""

    def test_uniform_costs_balanced_split(self):
        from dlrover_tpu.parallel.planner import plan_stage_depths

        # 30 layers over 4 stages: ceil/floor split, max chunk 8
        d = plan_stage_depths([1.0] * 30, num_stages=4)
        assert sum(d) == 30 and len(d) == 4
        assert max(d) == 8 and min(d) >= 7

    def test_interleaved_chunks(self):
        from dlrover_tpu.parallel.planner import plan_stage_depths

        d = plan_stage_depths([1.0] * 6, num_stages=2, num_virtual=2)
        assert len(d) == 4 and sum(d) == 6
        assert max(d) == 2  # balanced: (2, 2, 1, 1) up to rotation

    def test_heterogeneous_costs_shift_layers(self):
        from dlrover_tpu.parallel.planner import plan_stage_depths

        # one 4x-cost layer at the front: the DP gives its chunk fewer
        # layers so the max chunk cost stays near the mean
        costs = [4.0] + [1.0] * 7
        d = plan_stage_depths(costs, num_stages=2)
        assert sum(d) == 8
        assert d[0] < d[1]  # expensive front chunk carries fewer layers

    def test_feeds_strategy(self):
        from dlrover_tpu.parallel.planner import plan_stage_depths
        from dlrover_tpu.parallel.strategy import Strategy

        d = plan_stage_depths([1.0] * 6, num_stages=2, num_virtual=2)
        s = Strategy(rule_set="llama_pp", num_virtual=2, stage_depths=d)
        assert Strategy.from_json(s.to_json()).stage_depths == d


class TestPipeEstimateRefinements:
    """The pipeline compute model prices the circular schedule, uneven
    slot overhead, and the stage-boundary remat floor."""

    def _spec(self):
        from dlrover_tpu.models import llama
        from dlrover_tpu.parallel import planner

        cfg = llama.llama3_70b()
        return (planner.model_spec_from_llama(cfg, 32),
                planner.TPU_SPECS["v5p"])

    def test_interleaving_shrinks_bubble(self):
        from dlrover_tpu.parallel import planner
        from dlrover_tpu.parallel.mesh import MeshPlan

        m, spec = self._spec()
        plan = MeshPlan(pipe=4, data=4, tensor=4)
        v1 = planner.estimate(plan, m, spec, remat_policy="dots_saveable",
                              pipe_microbatches=8, pipe_virtual=1)
        v2 = planner.estimate(plan, m, spec, remat_policy="dots_saveable",
                              pipe_microbatches=8, pipe_virtual=2)
        assert v2.step_time_s < v1.step_time_s

    def test_uneven_depths_cost_slot_overhead(self):
        from dlrover_tpu.parallel import planner
        from dlrover_tpu.parallel.mesh import MeshPlan

        m, spec = self._spec()
        plan = MeshPlan(pipe=4, data=4, tensor=4)
        even = planner.estimate(plan, m, spec,
                                remat_policy="dots_saveable",
                                pipe_microbatches=8, pipe_virtual=2)
        uneven = planner.estimate(
            plan, m, spec, remat_policy="dots_saveable",
            pipe_microbatches=8, pipe_virtual=2,
            stage_depths=(9, 11, 11, 9, 9, 11, 11, 9),
        )
        # 8 chunks x Lmax 11 slots over 80 real layers = 1.10x compute
        ratio = uneven.step_time_s / even.step_time_s
        assert 1.05 < ratio < 1.15, ratio

    def test_pipelined_remat_floors_at_save_nothing(self):
        from dlrover_tpu.parallel import planner
        from dlrover_tpu.parallel.mesh import MeshPlan

        m, spec = self._spec()
        pp = MeshPlan(pipe=4, data=4, tensor=4)
        flat = MeshPlan(data=4, fsdp=4, tensor=4)
        pp_score = planner.estimate(pp, m, spec,
                                    remat_policy="dots_saveable")
        flat_score = planner.estimate(flat, m, spec,
                                      remat_policy="dots_saveable")
        full = planner.REMAT_RECOMPUTE["full"]
        saveable = planner.REMAT_RECOMPUTE["dots_saveable"]
        assert pp_score.breakdown["exec_flops"] == pytest.approx(
            flat_score.breakdown["exec_flops"] * full / saveable
        )
        # no remat -> no stage replay, no floor
        none_pp = planner.estimate(pp, m, spec, remat_policy="none")
        assert none_pp.breakdown["exec_flops"] == pytest.approx(
            flat_score.breakdown["exec_flops"] / saveable
        )


class TestMoEDispatchPricing:
    """estimate() prices the MoE dispatch per ``model.moe_dispatch``:
    the capacity fallback's one-hot einsums are QUADRATIC in per-chip
    tokens while grouped_ep's two all-to-alls are LINEAR — the planner
    must rank the two honestly on both sides of the crossover."""

    def _moe_spec(self, global_batch, dispatch, seq_len=2048):
        return ModelSpec(
            param_count=25_000_000_000, num_layers=32, hidden_size=4096,
            seq_len=seq_len, global_batch=global_batch,
            num_experts=8, moe_top_k=1, moe_capacity_factor=1.25,
            moe_dispatch=dispatch,
        )

    def test_dense_model_unaffected(self):
        spec = _llama7b_spec()
        s = estimate(MeshPlan(data=2, fsdp=4), spec, TPU_SPECS["v5p"])
        assert s.breakdown["moe_disp_comp_s"] == 0.0
        assert s.breakdown["moe_disp_comm_s"] == 0.0

    def test_gather_under_ep_priced_quadratic(self):
        """Doubling per-chip tokens quadruples the capacity fallback's
        dispatch compute but only doubles grouped_ep's all-to-all
        bytes."""
        plan = MeshPlan(data=2, fsdp=4)
        dev = TPU_SPECS["v5p"]
        g1 = estimate(plan, self._moe_spec(8, "gather"), dev)
        g2 = estimate(plan, self._moe_spec(16, "gather"), dev)
        e1 = estimate(plan, self._moe_spec(8, "grouped_ep"), dev)
        e2 = estimate(plan, self._moe_spec(16, "grouped_ep"), dev)
        assert g2.breakdown["moe_disp_comp_s"] == pytest.approx(
            4.0 * g1.breakdown["moe_disp_comp_s"]
        )
        assert e2.breakdown["moe_disp_comm_s"] == pytest.approx(
            2.0 * e1.breakdown["moe_disp_comm_s"]
        )
        assert g1.breakdown["moe_disp_comm_s"] == 0.0
        assert e1.breakdown["moe_disp_comp_s"] == 0.0

    def test_grouped_ep_vs_gather_ranking_flips_with_tokens(self):
        """The acceptance crossover: at small per-chip token counts the
        capacity fallback's quadratic dispatch is cheap and "gather"
        ranks faster; at large counts it dwarfs grouped_ep's linear
        all-to-all bytes and the ranking flips."""
        plan = MeshPlan(data=2, fsdp=4)
        dev = TPU_SPECS["v5e"]
        small_g = estimate(plan, self._moe_spec(8, "gather"), dev)
        small_e = estimate(plan, self._moe_spec(8, "grouped_ep"), dev)
        big_g = estimate(plan, self._moe_spec(256, "gather"), dev)
        big_e = estimate(plan, self._moe_spec(256, "grouped_ep"), dev)
        assert small_g.step_time_s < small_e.step_time_s, (
            small_g.step_time_s, small_e.step_time_s
        )
        assert big_e.step_time_s < big_g.step_time_s, (
            big_e.step_time_s, big_g.step_time_s
        )

    def test_no_ep_submesh_prices_per_shard(self):
        """With data=fsdp=1 there is no expert submesh: gather prices
        its linear slot-gather HBM term, not the quadratic fallback,
        and grouped_ep (degraded to per-shard) pays no ICI."""
        plan = MeshPlan(data=1, fsdp=1, tensor=8)
        dev = TPU_SPECS["v5p"]
        g = estimate(plan, self._moe_spec(8, "gather"), dev)
        e = estimate(plan, self._moe_spec(8, "grouped_ep"), dev)
        assert g.breakdown["moe_disp_comm_s"] == 0.0
        assert e.breakdown["moe_disp_comm_s"] == 0.0
        # the per-shard term is LINEAR in tokens (slot-gather HBM),
        # not the EP fallback's quadratic einsums
        g2 = estimate(plan, self._moe_spec(16, "gather"), dev)
        assert g2.breakdown["moe_disp_comp_s"] == pytest.approx(
            2.0 * g.breakdown["moe_disp_comp_s"]
        )

    def test_model_spec_from_llama_carries_moe(self):
        from dlrover_tpu.models import llama
        from dlrover_tpu.parallel.planner import model_spec_from_llama

        cfg = llama.llama_tiny(num_experts=8, moe_top_k=2,
                               moe_dispatch="grouped_ep")
        spec = model_spec_from_llama(cfg, 16)
        assert spec.num_experts == 8
        assert spec.moe_top_k == 2
        assert spec.moe_dispatch == "grouped_ep"


class TestStageRematFlag:
    """estimate(stage_remat=...) overrides the strategy-string
    inference: the models key stage-boundary remat off the MODEL
    config's policy (apply_pipelined), so aot passes the truth."""

    def test_explicit_stage_remat_beats_string_inference(self):
        spec = _llama7b_spec()
        plan = MeshPlan(pipe=4, data=2)
        dev = TPU_SPECS["v5p"]
        # strategy string empty but the model remats its stages: the
        # replay factor must appear (the ADVICE r5 #4 gap)
        inferred = estimate(plan, spec, dev, remat_policy="")
        explicit = estimate(plan, spec, dev, remat_policy="",
                            stage_remat=True)
        assert explicit.breakdown["exec_flops"] == pytest.approx(
            inferred.breakdown["exec_flops"] * 8.0 / 6.0
        )
        # and the reverse: strategy says full but the model does not
        # apply stage remat -> no bump past the policy's own factor
        off = estimate(plan, spec, dev, remat_policy="full",
                       stage_remat=False)
        on = estimate(plan, spec, dev, remat_policy="full",
                      stage_remat=True)
        assert off.breakdown["exec_flops"] == on.breakdown["exec_flops"]

    def test_none_preserves_inference(self):
        spec = _llama7b_spec()
        plan = MeshPlan(pipe=4, data=2)
        dev = TPU_SPECS["v5p"]
        a = estimate(plan, spec, dev, remat_policy="dots_saveable")
        b = estimate(plan, spec, dev, remat_policy="dots_saveable",
                     stage_remat=None)
        assert a.step_time_s == b.step_time_s


class TestDevicePreloaderGlobalRows:
    """DevicePreloader threads the expected global row count into
    put_global_batch so a multi-host caller feeding the GLOBAL batch
    fails loudly instead of silently assembling a process_count-times
    duplicated batch."""

    class _NonAddressable:
        # a sharding spanning other processes' devices: put_global_batch
        # takes the make_array_from_process_local_data path
        is_fully_addressable = False

    def test_wrong_local_rows_fail_loudly(self):
        # process_count=1 here, so expected = global_rows = 8; feeding
        # 4 rows must raise the loud contract error BEFORE assembly
        pre_bad = DevicePreloader(
            [{"x": np.zeros((4, 4))}],
            sharding=self._NonAddressable(),
            global_rows=8,
        )
        with pytest.raises(ValueError, match="PROCESS-LOCAL"):
            next(iter(pre_bad))

    def test_zero_global_rows_skips_validation(self):
        # global_rows=0 (the default): no row check — the batch
        # proceeds to assembly, which dies on the fake sharding with
        # some jax-internal error, NOT the contract message
        pre = DevicePreloader(
            [{"x": np.zeros((4, 4))}],
            sharding=self._NonAddressable(),
        )
        with pytest.raises(Exception) as ei:
            next(iter(pre))
        assert "PROCESS-LOCAL" not in str(ei.value)
