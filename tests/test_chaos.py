"""Chaos tests: REAL faults (SIGKILL, flaky rpc, torn checkpoint) against
real components — the integration layer mocked-fault unit tests miss.
"""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.training_agent import (
    AgentConfig,
    ElasticTrainingAgent,
)
from dlrover_tpu.agent.worker_group import WorkerSpec
from dlrover_tpu.diagnosis.fault_injection import (
    corrupt_checkpoint,
    kill_workers,
    make_flaky,
)
from dlrover_tpu.master.local_master import start_local_master

TESTDATA = os.path.join(os.path.dirname(__file__), "testdata")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER_ENV = {
    "PYTHONPATH": REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
}


@pytest.fixture()
def master():
    m = start_local_master()
    yield m
    m.stop()


def _derived_mttr(events_path):
    """Run the real CLI derivation over a chaos run's event timeline."""
    from dlrover_tpu.telemetry import read_events
    from dlrover_tpu.telemetry.mttr import mttr_report

    return mttr_report(read_events(events_path))


def test_external_sigkill_triggers_restart(master, tmp_path, monkeypatch):
    """A worker killed from OUTSIDE (SIGKILL, like an OOM killer or
    preemption — not a polite exception) must be detected by the monitor
    loop and restarted within the budget."""
    events_path = str(tmp_path / "events.jsonl")
    monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", events_path)
    client = MasterClient(master.addr, node_id=0)
    config = AgentConfig(
        node_rank=0, node_id=0, nproc_per_node=1, min_nodes=1, max_nodes=1,
        max_restarts=2, monitor_interval=0.2, rdzv_waiting_timeout=5.0,
    )
    spec = WorkerSpec(
        entrypoint=os.path.join(TESTDATA, "chaos_worker.py"),
        nproc_per_node=1, env=dict(WORKER_ENV),
    )
    agent = ElasticTrainingAgent(config, spec, client, host_ip="127.0.0.1")

    result = {}
    thread = threading.Thread(
        target=lambda: result.update(rc=agent.run()), daemon=True
    )
    thread.start()

    # wait for the round-0 worker process, then SIGKILL it
    deadline = time.monotonic() + 30
    pids = []
    while time.monotonic() < deadline:
        procs = getattr(agent._worker_group, "_procs", [])
        pids = [p.pid for p in procs if p.poll() is None]
        if pids:
            break
        time.sleep(0.1)
    assert pids, "worker never spawned"
    assert kill_workers(pids)

    thread.join(timeout=60)
    assert not thread.is_alive(), "agent did not finish after chaos kill"
    assert result["rc"] == 0
    assert agent._worker_group.restart_round >= 1
    # the MTTR artifact is DERIVED from the timeline this run produced:
    # worker_failed (SIGKILL classified by exit code) -> workers_started
    report = _derived_mttr(events_path)
    wf = report["detail"]["by_scenario"].get("worker_failure")
    assert wf and wf["count"] >= 1, report
    assert report["value"] > 0
    assert "error" not in report, report

    # -- cross-process trace correlation: the incident id minted at
    # failure detection must stamp the AGENT's failure edge, the
    # MASTER's ingress-side error_report (propagated through gRPC
    # metadata), the recovery edge, and the relaunched WORKER's own
    # startup events (propagated through the worker environment)
    from dlrover_tpu.telemetry import read_events

    records = read_events(events_path)
    failed = [r for r in records if r["kind"] == "worker_failed"]
    assert failed, records
    tid = failed[0].get("trace_id", "")
    assert tid.startswith("inc-"), failed[0]
    stamped = {r["kind"] for r in records if r.get("trace_id") == tid}
    assert "error_report" in stamped, stamped  # master ingress (RPC md)
    assert "workers_started" in stamped, stamped  # agent recovery edge
    assert "train_start" in stamped, stamped  # relaunched worker (env)
    stamped_pids = {r["pid"] for r in records
                    if r.get("trace_id") == tid}
    assert len(stamped_pids) >= 2, (
        "the incident id never crossed a process boundary")

    # -- merged Perfetto trace: the incident's master/agent/worker
    # records land in ONE view, joined by the shared trace id
    from dlrover_tpu.telemetry.correlate import (
        export_merged_trace,
        incident_records,
    )

    merged_path = str(tmp_path / "merged_trace.json")
    n = export_merged_trace(records, merged_path)
    assert n > 0
    import json

    payload = json.load(open(merged_path))
    names_seen = {e["name"] for e in payload["traceEvents"]}
    assert "worker_failure" in names_seen  # incident downtime span
    chain = incident_records(records)[tid]
    assert len(chain) >= 3

    # -- goodput ledger over the same timeline: buckets partition the
    # job wall-time (>= 99%) and the restart downtime is attributed
    from dlrover_tpu.telemetry.goodput import derive_goodput

    ledger = derive_goodput(records)
    assert ledger["detail"]["coverage"] >= 0.99, ledger
    assert ledger["detail"]["buckets"]["restart"]["seconds"] > 0, ledger

    # -- the CLI gate: `tpurun goodput` / `tpurun diagnose` must keep
    # working against a real chaos timeline (exit 0, parseable output)
    from dlrover_tpu.trainer.run import main as tpurun

    assert tpurun(["goodput", "--events", events_path]) == 0
    assert tpurun(["diagnose", "--events", events_path]) == 0
    assert tpurun(["trace", "--events", events_path,
                   "--out", str(tmp_path / "cli_trace.json")]) == 0


def test_hang_without_heartbeat_triggers_relaunch(master, tmp_path,
                                                  monkeypatch):
    """A worker whose process stays alive but whose step loop freezes
    (the TPU hang mode: a collective waiting on a dead peer) must be
    detected via the heartbeat gap and relaunched — the reference's
    --relaunch_on_hanging semantics."""
    events_path = str(tmp_path / "events.jsonl")
    monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", events_path)
    client = MasterClient(master.addr, node_id=0)
    config = AgentConfig(
        node_rank=0, node_id=0, nproc_per_node=1, min_nodes=1, max_nodes=1,
        max_restarts=2, monitor_interval=0.2, rdzv_waiting_timeout=5.0,
        # must exceed worker python startup on a loaded 1-core host, or
        # the restarted round gets falsely flagged before its first beat
        hang_timeout=8.0,
    )
    spec = WorkerSpec(
        entrypoint=os.path.join(TESTDATA, "hang_worker.py"),
        nproc_per_node=1, env=dict(WORKER_ENV),
    )
    agent = ElasticTrainingAgent(config, spec, client, host_ip="127.0.0.1")
    rc = agent.run()
    assert rc == 0
    assert agent._worker_group.restart_round >= 1
    # the hang was reported to the master's failure log as node 0
    assert 0 in client.failed_nodes()
    client.close()
    # derived MTTR: hang_detected -> workers_started, with the HANG
    # error code carried on the failure edge
    from dlrover_tpu.telemetry import read_events

    report = _derived_mttr(events_path)
    hang = report["detail"]["by_scenario"].get("hang")
    assert hang and hang["count"] >= 1, report
    assert report["value"] > 0
    hang_edges = [r for r in read_events(events_path)
                  if r["kind"] == "hang_detected"]
    assert hang_edges and hang_edges[0]["error_code"] == "HANG"


def test_long_phase_lease_defers_hang_judgment(tmp_path):
    """A declared bounded no-beat window (recompile/restore lease) must
    count as liveness until its deadline — and a stale lease from before
    a restart must not extend the fresh round's clock."""
    from dlrover_tpu.agent.worker_group import WorkerGroup, WorkerSpec

    spec = WorkerSpec(entrypoint="x", heartbeat_dir=str(tmp_path))
    group = WorkerGroup(spec)
    group.started_at = time.time() - 100  # round began 100 s ago

    # no beats, no lease: gap is the full 100 s
    latest, beaten = group.latest_heartbeat()
    assert not beaten and time.time() - latest > 90

    # write the lease through the REAL producer (announce_long_phase) —
    # the heartbeat dir itself contains "hb_" like the agent's tempdir,
    # which a naive whole-path prefix swap would corrupt
    import dlrover_tpu.diagnosis.hang_detector as hd
    from dlrover_tpu.common.constants import NodeEnv

    hb_dir = tmp_path / "dlrover_hb_test"
    old_env = os.environ.get(NodeEnv.HEARTBEAT_DIR)
    os.environ[NodeEnv.HEARTBEAT_DIR] = str(hb_dir)
    hd._heartbeat_path = None
    hd._heartbeat_resolved = False
    try:
        spec2 = WorkerSpec(entrypoint="x", heartbeat_dir=str(hb_dir))
        group2 = WorkerGroup(spec2)
        group2.started_at = time.time() - 100
        hd.announce_long_phase(300)
        assert (hb_dir / "lease_0").exists()
        latest, _ = group2.latest_heartbeat()
        assert time.time() - latest < 5

        # the next heartbeat (phase over) clears the lease
        hd.touch_heartbeat()
        assert not (hb_dir / "lease_0").exists()

        # a stale lease is ignored once a new round starts after it
        hd.announce_long_phase(300)
        group2.started_at = time.time() + 1
        latest, _ = group2.latest_heartbeat()
        assert latest == group2.started_at
    finally:
        hd._heartbeat_path = None
        hd._heartbeat_resolved = False
        if old_env is None:
            os.environ.pop(NodeEnv.HEARTBEAT_DIR, None)
        else:
            os.environ[NodeEnv.HEARTBEAT_DIR] = old_env


# budget triage (PR 16): retry counting + desynchronized backoff are
# pinned tier-1 by test_replication's flaky-servicer test; the full
# agent-chaos variant rides slow
@pytest.mark.slow
def test_flaky_rpc_absorbed_by_retries(master):
    """Inject UNAVAILABLE below the retry decorator on a deterministic
    fraction of calls; the dynamic-sharding flow must still complete."""
    client = MasterClient(master.addr, node_id=0)
    stats = make_flaky(client._channel, drop_rate=0.25, seed=7)

    client.report_dataset_shard_params(
        dataset_name="chaos_ds", dataset_size=24, batch_size=3,
        num_epochs=1, num_minibatches_per_shard=2,
    )
    # a post-call injected fault on get_task LOSES the response: the shard
    # sits in "doing" until the timeout monitor requeues it. Drive that
    # recovery deterministically (timeout=0 == one monitor tick) between
    # drain rounds — completion must survive both fault modes.
    done = 0
    for _attempt in range(6):
        while True:
            task = client.get_task("chaos_ds")
            if task is None or task.task_id < 0:
                break
            client.report_task_result("chaos_ds", task.task_id)
            done += 1
        if done >= 4:
            break
        dataset = master.task_manager.get_dataset("chaos_ds")
        dataset.recover_timeout_tasks(0)
    assert done == 4  # 24 records / (3*2) per shard, every shard completed
    assert stats.injected > 0, "no faults were actually injected"
    client.close()


def test_peer_rebuild_after_sigkill_is_bitwise_and_storage_free(
        master, tmp_path, monkeypatch):
    """The checkpoint-free recovery wedge (ISSUE 15 acceptance):
    SIGKILL a worker whose snapshot regions are replicated on a
    surviving peer -> the master's verdict excludes the dead node from
    holder lists -> the relaunched worker rebuilds its state by
    STREAMING it out of the peer's DRAM (no checkpoint directory even
    exists) -> its post-recovery steps are BITWISE an uninterrupted
    run's, the whole recovery rides ONE incident trace id across >= 2
    pids, and the MTTR/goodput derivations record the peer_rebuild
    scenario with zero storage bytes."""
    import subprocess
    import sys

    from dlrover_tpu.checkpoint import replication as repl

    events_path = str(tmp_path / "events.jsonl")
    monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", events_path)
    # the MASTER owns k ("k peer agents chosen by the master"): the
    # plan request is priced against ITS Context knob, so the master
    # process — pytest here — must carry it, not just the workers
    from dlrover_tpu.common.config import get_context

    monkeypatch.setattr(get_context(), "snapshot_replicas", 1)
    # the surviving peer: an in-test replica store registered as node 9
    # (its process — pytest — survives the worker's death)
    store = repl.ReplicaStore()
    srv, port = repl.start_replica_server(store, host="127.0.0.1")
    holder_client = MasterClient(master.addr, node_id=9)
    holder_client.report_replica_endpoint(
        addr=f"127.0.0.1:{port}", budget_mb=64.0, snapshot_mb=0.0,
        step=-1)

    status = tmp_path / "status.jsonl"
    worker_env = {
        **WORKER_ENV,
        "PEER_STATUS": str(status),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",
        "DLROVER_TPU_SNAPSHOT_REPLICAS": "1",
        "DLROVER_TPU_REPLICA_CADENCE_STEPS": "2",
        "DLROVER_TPU_REPLICA_MIN_INTERVAL_SECS": "0",
        "DLROVER_TPU_PEER_RESTORE": "true",
    }
    config = AgentConfig(
        node_rank=0, node_id=0, nproc_per_node=1, min_nodes=1,
        max_nodes=1, max_restarts=2, monitor_interval=0.2,
        rdzv_waiting_timeout=5.0,
    )
    spec = WorkerSpec(
        entrypoint=os.path.join(TESTDATA, "peer_worker.py"),
        nproc_per_node=1, env=worker_env,
    )
    client = MasterClient(master.addr, node_id=0)
    agent = ElasticTrainingAgent(config, spec, client,
                                 host_ip="127.0.0.1")
    result = {}
    thread = threading.Thread(
        target=lambda: result.update(rc=agent.run()), daemon=True
    )
    thread.start()
    try:
        # wait until a replica has COMMITTED on the surviving peer,
        # then SIGKILL the worker mid-step
        deadline = time.monotonic() + 120
        pids = []
        while time.monotonic() < deadline:
            procs = getattr(agent._worker_group, "_procs", [])
            pids = [p.pid for p in procs if p.poll() is None]
            if pids and store.inventory().get("0"):
                break
            time.sleep(0.1)
        assert store.inventory().get("0"), \
            "no replica ever committed on the surviving peer"
        assert pids and kill_workers(pids)

        thread.join(timeout=180)
        assert not thread.is_alive(), "agent never finished"
        assert result["rc"] == 0
        assert agent._worker_group.restart_round >= 1
    finally:
        holder_client.close()
        client.close()
        srv.stop(grace=0)

    # -- the recovered run resumed at the replicated step and finished
    records = [json.loads(ln) for ln in
               status.read_text().splitlines()]
    ends = [r for r in records if r.get("event") == "end"]
    assert ends, records[-3:]
    end = ends[-1]
    assert end["round"] >= 1
    resumed = end["resumed_step"]
    assert resumed >= 2, "relaunched worker did not peer-restore"
    assert end["final_step"] == resumed + 3
    # the relaunched worker keeps replicating: the surviving peer's
    # freshest commit is at (or past) the recovered run's progress
    assert store.inventory()["0"]["manifest"]["meta"][
        "host_step"] >= resumed

    # -- bitwise: an UNINTERRUPTED run to the same step produces the
    # identical params (same rng stream, same batches — the rebuild
    # lost nothing and invented nothing)
    ref_status = tmp_path / "ref_status.jsonl"
    ref_env = {
        **os.environ, **WORKER_ENV,
        "PEER_STATUS": str(ref_status),
        "PEER_REFERENCE": "1",
        "PEER_TOTAL_STEPS": str(end["final_step"]),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",
        "DLROVER_TPU_SNAPSHOT_REPLICAS": "0",
    }
    ref_env.pop("PALLAS_AXON_POOL_IPS", None)
    ref = subprocess.run(
        [sys.executable, os.path.join(TESTDATA, "peer_worker.py")],
        env=ref_env, timeout=180,
    )
    assert ref.returncode == 0
    ref_end = [json.loads(ln) for ln in
               ref_status.read_text().splitlines()][-1]
    assert ref_end["final_step"] == end["final_step"]
    assert ref_end["digest"] == end["digest"], (
        "post-recovery params diverged from the uninterrupted run")

    # -- zero storage reads on the recovery path, derived + asserted
    from dlrover_tpu.telemetry import read_events

    timeline = read_events(events_path)
    done = [r for r in timeline if r["kind"] == "peer_rebuild_done"]
    assert done, "no peer_rebuild_done edge in the timeline"
    assert done[-1]["storage_bytes"] == 0
    assert done[-1]["bytes_from_peers"] > 0

    # -- the rung the worker walked was PRICED: both the prediction it
    # fetched with the recovery plan and the realized fetch+put cost
    # are stamped on the recovery event, and the prediction is within
    # 2x of reality either way (the readiness acceptance pin — the
    # link_bw term is calibrated from the replicator's own push cycles
    # over this same localhost RPC path)
    predicted = done[-1].get("predicted_mttr_s")
    realized = done[-1].get("realized_mttr_s")
    assert predicted is not None and predicted > 0, done[-1]
    assert realized is not None and realized > 0, done[-1]
    assert done[-1].get("rung") == "peer_rebuild"
    assert predicted <= 2.0 * realized + 0.05, (predicted, realized)
    assert realized <= 2.0 * predicted + 0.05, (predicted, realized)
    assert not [r for r in timeline if r["kind"] == "ckpt_restore"], (
        "the recovery path touched storage")

    # -- one incident trace id spans agent-side failure detection and
    # the relaunched worker's peer rebuild (>= 2 pids)
    failed = [r for r in timeline if r["kind"] == "worker_failed"]
    assert failed
    tid = failed[0].get("trace_id", "")
    assert tid.startswith("inc-")
    stamped = {r["kind"] for r in timeline
               if r.get("trace_id") == tid}
    assert "peer_rebuild_done" in stamped, stamped
    assert "workers_started" in stamped, stamped
    pids_stamped = {r["pid"] for r in timeline
                    if r.get("trace_id") == tid}
    assert len(pids_stamped) >= 2

    # -- the MTTR scenario + goodput ledger record the recovery
    report = _derived_mttr(events_path)
    pr = report["detail"]["by_scenario"].get("peer_rebuild")
    assert pr and pr["count"] >= 1, report
    wf = report["detail"]["by_scenario"].get("worker_failure")
    assert wf and wf["count"] >= 1, report
    from dlrover_tpu.telemetry.goodput import derive_goodput

    ledger = derive_goodput(timeline)
    assert ledger["detail"]["coverage"] >= 0.99, ledger
    assert ledger["detail"]["buckets"]["peer_rebuild"]["seconds"] > 0


@pytest.mark.slow
def test_kill_restart_soak(master):
    """Repeated external SIGKILL cycles: every round must be detected,
    reported, and restarted until the budget genuinely runs out —
    recovery machinery that only survives ONE fault is not recovery."""
    rounds = 3
    client = MasterClient(master.addr, node_id=0)
    config = AgentConfig(
        node_rank=0, node_id=0, nproc_per_node=1, min_nodes=1, max_nodes=1,
        max_restarts=rounds, monitor_interval=0.2,
        rdzv_waiting_timeout=5.0,
    )
    spec = WorkerSpec(
        entrypoint=os.path.join(TESTDATA, "soak_worker.py"),
        nproc_per_node=1, env=dict(WORKER_ENV),
    )
    agent = ElasticTrainingAgent(config, spec, client, host_ip="127.0.0.1")
    result = {}
    thread = threading.Thread(
        target=lambda: result.update(rc=agent.run()), daemon=True
    )
    thread.start()

    killed = 0
    deadline = time.monotonic() + 120
    while killed < rounds and time.monotonic() < deadline:
        procs = getattr(agent._worker_group, "_procs", [])
        pids = [p.pid for p in procs if p.poll() is None]
        round_now = agent._worker_group.restart_round
        if pids and round_now == killed:
            time.sleep(0.5)  # let the round take a breath, then kill it
            if kill_workers(pids):
                killed += 1
        time.sleep(0.1)
    assert killed == rounds, f"only injected {killed}/{rounds} kills"

    thread.join(timeout=60)
    assert not thread.is_alive()
    assert result["rc"] == 0  # final (uninjected) round completes
    assert agent._worker_group.restart_round == rounds


def test_corrupt_latest_checkpoint_falls_back(tmp_path):
    """Torn-write the newest checkpoint; restore must come back from the
    newest GOOD step instead of crashing."""
    from dlrover_tpu.checkpoint.manager import (
        ElasticCheckpointManager,
        abstract_like,
    )

    mgr = ElasticCheckpointManager(
        str(tmp_path / "ckpt"), async_save=False, staging_dir="",
    )
    state = {"w": jnp.full((64, 64), 1.0), "step": jnp.asarray(1)}
    assert mgr.save(1, state, force=True)
    state2 = {"w": jnp.full((64, 64), 2.0), "step": jnp.asarray(2)}
    assert mgr.save(2, state2, force=True)
    mgr.wait()

    step2_dir = mgr._step_dir(mgr.directory, 2)
    assert os.path.isdir(step2_dir)
    assert corrupt_checkpoint(step2_dir, mode="truncate") is not None

    out = mgr.restore(abstract_like(state))
    assert out is not None
    assert out["step"] == 1
    np.testing.assert_allclose(np.asarray(out["state"]["w"]), 1.0)

    # the corrupt step must be quarantined: otherwise it keeps winning
    # latest_step() and blocks the resumed job's re-save at step 2
    assert mgr.latest_step() == 1
    assert not os.path.isdir(step2_dir)
    assert mgr.save(2, state2, force=True), (
        "re-save at the quarantined step number must be accepted"
    )
    mgr.wait()
    assert mgr.latest_step() == 2
    out2 = mgr.restore(abstract_like(state))
    assert out2["step"] == 2
    np.testing.assert_allclose(np.asarray(out2["state"]["w"]), 2.0)
    mgr.close()


def test_corrupt_primary_recovers_same_step_from_staging(tmp_path):
    """When the primary copy of the latest step is torn but the host-DRAM
    mirror still holds that step (digest gate rejects it only because the
    PRIMARY is now corrupt), the fallback must restore the SAME step from
    staging — losing zero progress — and quarantine the bad primary."""
    from dlrover_tpu.checkpoint.manager import (
        ElasticCheckpointManager,
        abstract_like,
    )

    mgr = ElasticCheckpointManager(
        str(tmp_path / "ckpt"), async_save=False,
        staging_dir=str(tmp_path / "shm"),
    )
    state1 = {"w": jnp.full((64, 64), 1.0), "step": jnp.asarray(1)}
    state2 = {"w": jnp.full((64, 64), 2.0), "step": jnp.asarray(2)}
    assert mgr.save(1, state1, force=True)
    mgr.wait()
    assert mgr.save(2, state2, force=True)
    mgr.wait()
    assert mgr.staged_step() == 2

    corrupt_checkpoint(mgr._step_dir(mgr.directory, 2), mode="truncate")
    out = mgr.restore(abstract_like(state1))
    assert out is not None
    assert out["step"] == 2, "staging held step 2 — no progress loss"
    np.testing.assert_allclose(np.asarray(out["state"]["w"]), 2.0)
    assert not os.path.isdir(mgr._step_dir(mgr.directory, 2))
    mgr.close()


def test_primary_loss_recovers_from_staging_across_restart(tmp_path):
    """The storage-outage story end to end ACROSS a process restart: the
    primary root is wiped, a new manager (same run identity) comes up,
    and the host-DRAM mirror restores — a path-local uuid would have
    been lost with the primary and wrongly rejected the mirror. A
    DIFFERENT job identity must still refuse the mirror."""
    import shutil

    from dlrover_tpu.checkpoint.manager import (
        ElasticCheckpointManager,
        abstract_like,
    )

    primary = str(tmp_path / "ckpt")
    staging = str(tmp_path / "shm")
    state = {"w": jnp.full((32, 32), 5.0), "step": jnp.asarray(3)}

    mgr1 = ElasticCheckpointManager(
        primary, async_save=False, staging_dir=staging,
        run_identity="jobA",
    )
    assert mgr1.save(3, state, force=True)
    mgr1.wait()
    assert mgr1.staged_step() == 3
    mgr1.close()

    shutil.rmtree(primary)  # the outage

    mgr2 = ElasticCheckpointManager(
        primary, async_save=False, staging_dir=staging,
        run_identity="jobA",
    )
    out = mgr2.restore(abstract_like(state))
    assert out is not None and out["step"] == 3
    np.testing.assert_allclose(np.asarray(out["state"]["w"]), 5.0)
    mgr2.close()

    shutil.rmtree(primary)
    mgr3 = ElasticCheckpointManager(
        primary, async_save=False, staging_dir=staging,
        run_identity="jobB",
    )
    assert mgr3.restore(abstract_like(state)) is None
    mgr3.close()


def test_shuffled_text_shards_honor_permutation(tmp_path):
    """A shuffled text dataset's shards carry record_indices; the batch
    source must train on that permutation, not contiguous ranges."""
    from dlrover_tpu.trainer.text_reader import (
        LineIndexedFile,
        ShardedTextBatches,
    )
    from dlrover_tpu.agent.sharding_client import ShardingClient

    path = tmp_path / "c.txt"
    path.write_text("".join(f"rec{i:03d}\n" for i in range(32)))
    reader = LineIndexedFile(str(path))

    m = start_local_master()
    try:
        client = MasterClient(m.addr, node_id=0)
        sc = ShardingClient(
            client, dataset_name="shuf", batch_size=4,
            dataset_size=reader.count(), num_epochs=1,
            num_minibatches_per_shard=1, shuffle=True,
            storage_type="text",
        )
        source = ShardedTextBatches(sc, reader, batch_size=4, seq_len=16)
        seen = []
        for batch in source:
            for row in batch["input_ids"]:
                chars = bytes(int(t) - 2 for t in row[1:] if t >= 2)
                seen.append(chars.decode())
        # every record consumed exactly once, and NOT in file order
        assert sorted(set(seen)) == [f"rec{i:03d}" for i in range(32)]
        assert seen != sorted(seen), "shuffle produced file order?"
        client.close()
    finally:
        m.stop()


def test_explicit_step_restore_still_raises_on_corruption(tmp_path):
    """Fallback only applies to auto-selected steps: explicitly asking for
    a specific (corrupt) step must fail loudly, not silently substitute."""
    from dlrover_tpu.checkpoint.manager import (
        ElasticCheckpointManager,
        abstract_like,
    )

    mgr = ElasticCheckpointManager(
        str(tmp_path / "ckpt"), async_save=False, staging_dir="",
    )
    state = {"w": jnp.full((64, 64), 1.0)}
    assert mgr.save(1, state, force=True)
    assert mgr.save(2, {"w": jnp.full((64, 64), 2.0)}, force=True)
    mgr.wait()
    corrupt_checkpoint(mgr._step_dir(mgr.directory, 2), mode="truncate")
    with pytest.raises(Exception):
        mgr.restore(abstract_like(state), step=2)
    mgr.close()


def _preempt_cycle(tmp_path, extra_env=None, step_deadline=120,
                   exit_wait=60, restart_timeout=180):
    """Shared preemption-grace protocol: run the preempt worker to >= 3
    steps, SIGTERM it, assert a clean in-grace exit, restart it against
    the emergency checkpoint, and return (killed_step, records)."""
    import json
    import signal
    import subprocess
    import sys

    script = os.path.join(TESTDATA, "preempt_worker.py")
    status = tmp_path / "status.jsonl"
    env = {
        **os.environ, **WORKER_ENV,
        "PREEMPT_CKPT_DIR": str(tmp_path / "ckpt"),
        "PREEMPT_STATUS": str(status),
        "JAX_PLATFORMS": "cpu",
        # default single-device worker: the conftest's 8-device forcing
        # would make ElasticTrainer adjust the 1x1 mesh to the full
        # world; pipelined callers override XLA_FLAGS themselves
        "XLA_FLAGS": "",
        **(extra_env or {}),
    }
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel

    def read_status():
        if not status.exists():
            return []
        out = []
        for ln in status.read_text().splitlines():
            try:
                out.append(json.loads(ln))
            except json.JSONDecodeError:
                pass  # torn write: next poll re-reads
        return out

    p = subprocess.Popen([sys.executable, script], env=env)
    try:
        deadline = time.time() + step_deadline
        steps = []
        while time.time() < deadline:
            steps = [r for r in read_status() if r.get("event") == "step"]
            if len(steps) >= 3:
                break
            assert p.poll() is None, (
                f"worker died rc={p.returncode} before 3 steps: "
                f"{read_status()[-3:]}"
            )
            time.sleep(0.2)
        assert len(steps) >= 3, "worker never reached 3 steps"
        p.send_signal(signal.SIGTERM)  # the preemption notice
        rc = p.wait(timeout=exit_wait)
    finally:
        if p.poll() is None:
            p.kill()
    # clean exit inside the grace window, not a crash
    assert rc == 0, f"worker exited {rc}"
    records = read_status()
    end = [r for r in records if r.get("event") == "end"]
    assert end and end[0]["preempted"] is True, records[-3:]
    killed_step = end[0]["final_step"]
    step_events = [r["step"] for r in records
                   if r.get("event") == "step"]
    # the save happened AT the in-flight step (<= 1 step of lost work)
    assert killed_step >= step_events[-1] - 1

    # restart: the worker must resume from the emergency checkpoint
    env["PREEMPT_TOTAL_STEPS"] = str(killed_step + 2)
    p2 = subprocess.run(
        [sys.executable, script], env=env, timeout=restart_timeout,
    )
    assert p2.returncode == 0
    records = read_status()
    begins = [r for r in records if r.get("event") == "begin"]
    assert len(begins) == 2, begins
    # the restart RESUMED from the emergency save, not from scratch,
    # and ran exactly the remaining steps
    assert begins[1]["resumed_step"] == killed_step, (
        f"resumed at {begins[1]['resumed_step']}, emergency save was at "
        f"{killed_step}"
    )
    ends = [r for r in records if r.get("event") == "end"]
    assert ends[-1]["final_step"] == killed_step + 2
    return killed_step, records


@pytest.mark.slow
def test_preemption_grace_saves_at_killed_step(tmp_path):
    """SIGTERM mid-training with NO periodic checkpoint cadence: the
    executor's preemption-grace handler flushes an emergency save at
    the in-flight step and exits cleanly; a restarted worker resumes at
    exactly that step — lost work <= 1 step, not the save cadence
    (reference design goal: flash checkpoint,
    ``docs/blogs/stabilize_llm_training_cn.md:215``)."""
    _preempt_cycle(tmp_path)


@pytest.mark.slow
def test_preemption_mid_window_drains_and_resumes(tmp_path):
    """SIGTERM while the ASYNC loop (train_window=4) has several step
    dispatches in flight: the executor drains the window — every
    dispatched step materializes — then flushes the emergency save at
    the last materialized step, and a restarted worker resumes exactly
    there. The shared cycle's invariants (clean in-grace exit, <= 1
    step lost, resume-at-killed-step, completion) all run against the
    pipelined loop."""
    killed_step, records = _preempt_cycle(
        tmp_path, extra_env={"PREEMPT_WINDOW": "4"},
    )
    # the drain materialized the full in-flight chain before the save:
    # the per-step status events reach the killed step with no holes
    step_events = [r["step"] for r in records if r.get("event") == "step"]
    pre_kill = [s for s in step_events if s <= killed_step]
    assert pre_kill == list(range(1, killed_step + 1)), pre_kill


@pytest.mark.slow
def test_preemption_grace_under_pipeline(tmp_path):
    """The SIGTERM preemption-grace save also holds when the worker is
    mid-PIPELINED training on a pipe mesh: the emergency checkpoint
    flushes pipe-sharded stage-stacked state, and the restarted worker
    resumes at the killed step through the same pipelined shardings."""
    killed_step, records = _preempt_cycle(
        tmp_path,
        extra_env={
            "PREEMPT_PIPELINE": "1",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        },
        step_deadline=180, exit_wait=90, restart_timeout=240,
    )
    assert killed_step >= 2  # the cycle's invariants all ran pipelined


@pytest.mark.slow
def test_second_sigterm_escapes_slow_step_without_corrupting_save(
    tmp_path,
):
    """Preemption grace under a SLOW device step (VERDICT r5 weak #5):
    the grace design finishes the in-flight step before saving, so when
    a step blocks for longer than the supervisor's patience the FIRST
    SIGTERM is flagged but never acted on. The handler's one-shot
    re-arm is the escape hatch: a SECOND SIGTERM must kill the process
    the ordinary way (no SIGTERM-proof worker), and the staged
    checkpoint chain committed by earlier steps must survive the hard
    kill — the restarted worker resumes from it, not from scratch."""
    import json
    import signal
    import subprocess
    import sys

    script = os.path.join(TESTDATA, "preempt_worker.py")
    status = tmp_path / "status.jsonl"
    env = {
        **os.environ, **WORKER_ENV,
        "PREEMPT_CKPT_DIR": str(tmp_path / "ckpt"),
        "PREEMPT_STATUS": str(status),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",
        "PREEMPT_SLOW_AFTER": "3",  # step 3 wedges for 300s
        "PREEMPT_SLOW_SECS": "300",
    }
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel

    def read_status():
        if not status.exists():
            return []
        out = []
        for ln in status.read_text().splitlines():
            try:
                out.append(json.loads(ln))
            except json.JSONDecodeError:
                pass  # torn write: next poll re-reads
        return out

    p = subprocess.Popen([sys.executable, script], env=env)
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            if any(r.get("event") == "slow" for r in read_status()):
                break
            assert p.poll() is None, (
                f"worker died rc={p.returncode} before wedging: "
                f"{read_status()[-3:]}"
            )
            time.sleep(0.2)
        assert any(r.get("event") == "slow" for r in read_status()), (
            "worker never reached the slow step"
        )
        p.send_signal(signal.SIGTERM)  # notice #1: flagged, swallowed
        time.sleep(2.0)
        # the loop is blocked inside the step path: the flag cannot be
        # checked, so the worker must still be alive (and would sit in
        # the wedge for the full 300s without the escape hatch)
        assert p.poll() is None, (
            f"first SIGTERM already ended the worker (rc={p.returncode})"
            " — the slow step never blocked the grace path"
        )
        p.send_signal(signal.SIGTERM)  # notice #2: the escape hatch
        rc = p.wait(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()
    # killed the ordinary way (default disposition), NOT a clean exit
    # and NOT a 300s hang
    assert rc != 0, "second SIGTERM should not exit 0 (no save ran)"
    records = read_status()
    assert not any(r.get("event") == "end" for r in records), (
        "wedged worker should die hard, not reach the end path"
    )
    steps = [r["step"] for r in records if r.get("event") == "step"]
    assert steps and max(steps) == 3

    # restart WITHOUT the wedge: the per-step staged saves from before
    # the kill must be uncorrupted — resume from one of them (>= 1),
    # never from scratch (0), and train to completion
    env.pop("PREEMPT_SLOW_AFTER")
    env.pop("PREEMPT_SLOW_SECS")
    env["PREEMPT_TOTAL_STEPS"] = "5"
    p2 = subprocess.run([sys.executable, script], env=env, timeout=180)
    assert p2.returncode == 0
    records = read_status()
    begins = [r for r in records if r.get("event") == "begin"]
    assert len(begins) == 2, begins
    resumed = begins[1]["resumed_step"]
    assert 1 <= resumed <= 3, (
        f"restart resumed at {resumed}: the staged save chain did not "
        f"survive the hard kill"
    )
    ends = [r for r in records if r.get("event") == "end"]
    assert ends and ends[-1]["final_step"] == 5
