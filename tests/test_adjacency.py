"""Regression gate for the checkpoint+executor same-process adjacency
hang.

Running ``tests/test_checkpoint_trainer.py`` and ``tests/test_executor.py``
in ONE pytest process used to wedge (or segfault) inside the first
donated train-step dispatch after an Orbax restore: on the CPU backend,
restored ``jax.Array``s could alias tensorstore-owned host buffers, and
``donate_argnums`` handed those buffers to XLA for reuse — a
use-after-donate that surfaced only once another Orbax manager had
touched the process's allocator state. Fixed by re-materializing every
restored state into XLA-owned buffers (``checkpoint.manager
._rematerialize``); this test pins EXACTLY the failing combination so
the hang cannot quietly return.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# budget triage (PR 16): a duplicate subprocess re-run of two files
# that already run tier-1 directly, guarding a long-fixed hang; it
# rides the slow tier
@pytest.mark.slow
def test_checkpoint_and_executor_files_share_one_process():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["PYTHONFAULTHANDLER"] = "1"
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest",
            "tests/test_checkpoint_trainer.py",
            "tests/test_executor.py",
            "-q", "-p", "no:cacheprovider", "-p", "no:randomly",
            "-m", "not slow",
        ],
        cwd=REPO, env=env, capture_output=True, text=True,
        # generous vs the ~13 s healthy runtime, far below the historic
        # infinite hang; a timeout here IS the regression signal
        timeout=300,
    )
    tail = (proc.stdout + proc.stderr)[-3000:]
    assert proc.returncode == 0, (
        f"two-file adjacency run failed (rc={proc.returncode}) — the "
        f"restore/donation hang may be back:\n{tail}"
    )
    assert " passed" in proc.stdout, tail
