"""Async parameter-server execution path.

Mirrors the reference's PS-strategy coverage: unit tests for placement and
framing, then a real local master + PS shard servers + async workers over
real gRPC (the ``test_elastic_training_agent.py`` in-process pattern), and a
migration/failover pass through the cluster-version handshake
(``tensorflow_failover.py`` parity).
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.master.local_master import start_local_master
from dlrover_tpu.ps import wire
from dlrover_tpu.ps.client import PsClusterClient, partition_params
from dlrover_tpu.ps.server import PsShardServer, start_ps_shard
from dlrover_tpu.ps.trainer import AsyncPsTrainer


# ---------------------------------------------------------------------------
# unit: wire + placement
# ---------------------------------------------------------------------------

def test_wire_roundtrip():
    tensors = {
        "w": np.arange(12, dtype=np.float32).reshape(3, 4),
        "b": np.ones((4,), np.float64),
        "i": np.array([1, 2, 3], np.int32),
    }
    frame = wire.pack_frame({"op": "push", "k": 7}, tensors)
    meta, out = wire.unpack_frame(frame)
    assert meta == {"op": "push", "k": 7}
    assert set(out) == set(tensors)
    for name in tensors:
        np.testing.assert_array_equal(out[name], tensors[name])
        assert out[name].dtype == tensors[name].dtype


def test_partition_balanced_and_deterministic():
    specs = {f"p{i}": (i + 1) * 100 for i in range(10)}
    a1 = partition_params(specs, 3)
    a2 = partition_params(dict(reversed(list(specs.items()))), 3)
    assert a1 == a2  # insertion order must not matter
    loads = {}
    for name, shard in a1.items():
        loads[shard] = loads.get(shard, 0) + specs[name]
    assert max(loads.values()) <= 2 * min(loads.values())
    assert set(a1.values()) == {0, 1, 2}


def test_numpy_optimizers_step():
    from dlrover_tpu.ps.server import _NpOptimizer
    for spec in ("sgd:0.1", "momentum:0.1:0.9", "adagrad:0.5", "adam:0.05"):
        opt = _NpOptimizer(spec)
        p = np.array([1.0, -2.0], np.float32)
        slots = opt.init_slots(p)
        before = p.copy()
        for _ in range(5):
            opt.apply(p, np.array([0.5, -0.5], np.float32), slots)
        # every optimizer moves against the gradient sign
        assert p[0] < before[0] and p[1] > before[1]


# ---------------------------------------------------------------------------
# integration: master + shards + async workers
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def master():
    m = start_local_master()
    yield m
    m.stop()


def _make_problem(seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(8, 1)).astype(np.float32)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    y = x @ w_true + 0.3
    return x, y


def _loss_fn(params, batch):
    x, y = batch
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def test_async_ps_training_two_workers(master, tmp_path):
    owner = MasterClient(master.addr, node_id=9)
    shards = [
        start_ps_shard(i, master_client=owner, optimizer="adagrad:0.3",
                       checkpoint_dir=str(tmp_path))
        for i in range(2)
    ]
    try:
        x, y = _make_problem()
        params0 = {"w": np.zeros((8, 1), np.float32),
                   "b": np.zeros((1,), np.float32)}

        trainers = []
        for node_id in range(2):
            mc = MasterClient(master.addr, node_id=node_id)
            cluster = PsClusterClient.discover(mc, num_shards=2)
            t = AsyncPsTrainer(_loss_fn, cluster, master_client=mc,
                               membership_check_every=0)
            trainers.append(t)
        trainers[0].init_params(params0)
        trainers[1].init_params(params0)  # idempotent second init

        first = trainers[0].step((x[:64], y[:64]))
        # interleave the two workers: genuinely async pushes
        last = first
        for i in range(120):
            t = trainers[i % 2]
            lo = (i * 32) % 192
            last = t.step((x[lo:lo + 64], y[lo:lo + 64]))
        assert last < first / 10, (first, last)

        # both shards hold a disjoint, complete slice
        stats = []
        for s in shards:
            meta, _ = wire.unpack_frame(s.call(wire.pack_frame(
                {"op": "stats"})))
            stats.append(meta)
        assert sum(m["num_params"] for m in stats) == 2
        assert all(m["version"] > 0 for m in stats)
    finally:
        for s in shards:
            s.stop()
        owner.close()


def test_ps_resize_via_checkpoint_repartition(master, tmp_path):
    """Grow the PS cluster 2 -> 3 shards: checkpoint, offline
    repartition, restart with restore, version bump — the worker drops
    its stale placement, recomputes it against the resized cluster, and
    training continues with optimizer state intact."""
    from dlrover_tpu.ps.repartition import repartition_checkpoint

    owner = MasterClient(master.addr, node_id=9)
    ckpt = str(tmp_path / "resize_ckpt")
    shards = [
        start_ps_shard(i, master_client=owner, optimizer="adagrad:0.3",
                       checkpoint_dir=ckpt, num_shards=2)
        for i in range(2)
    ]
    new_shards = []
    mc = MasterClient(master.addr, node_id=0)
    try:
        x, y = _make_problem(seed=2)
        cluster = PsClusterClient.discover(mc, num_shards=2)
        trainer = AsyncPsTrainer(_loss_fn, cluster, master_client=mc,
                                 membership_check_every=1)
        trainer.init_params({"w": np.zeros((8, 1), np.float32),
                             "b": np.zeros((1,), np.float32)})
        for _ in range(40):
            loss_before = trainer.step((x[:128], y[:128]))
        trainer.checkpoint()

        # the migration driver's sequence
        for s in shards:
            s.stop()
        assignment = repartition_checkpoint(ckpt, 2, 3)
        assert set(assignment.values()) <= {0, 1, 2}
        new_shards = [
            start_ps_shard(i, master_client=owner, optimizer="adagrad:0.3",
                           checkpoint_dir=ckpt, restore=True, num_shards=3)
            for i in range(3)
        ]
        cur = owner.get_cluster_version("global", "worker", 0)
        owner.update_cluster_version("global", cur + 1, "worker", 0,
                                     expected=cur)

        for _ in range(40):
            loss_after = trainer.step((x[:128], y[:128]))
        assert loss_after <= loss_before, (loss_before, loss_after)
        assert cluster.num_shards == 3
        # both parameters are placed against the resized cluster
        assert len(cluster._assignment) == 2
    finally:
        for s in shards + new_shards:
            s.stop()
        owner.close()
        mc.close()


def test_repartition_rerun_recovers_param_from_leftover_tmp(tmp_path):
    """The crash window between batched renames: a parameter whose old
    home was already rewritten but whose new home only exists as a tmp
    file must survive a rerun (ingested from the tmp, not dropped)."""
    from dlrover_tpu.ps.repartition import repartition_checkpoint

    d = str(tmp_path)
    # post-crash state: 'w' moved old-shard-0 -> new-shard-1; shard 0
    # already renamed (new payload, no w), shard 1 still old (no w),
    # the only copy of w sits in shard 1's tmp file
    np.savez(os.path.join(d, "ps-shard-0.npz"),
             **{"p/b": np.zeros((4,)), "__version__": np.asarray(7)})
    np.savez(os.path.join(d, "ps-shard-1.npz"),
             **{"p/e": np.ones((2, 2)), "__version__": np.asarray(7)})
    np.savez(os.path.join(d, "ps-shard-1.npz.tmp.npz"),
             **{"p/w": np.full((8, 8), 3.0),
                "s/w/acc": np.ones((8, 8)),
                "__version__": np.asarray(7)})
    # plus a TORN tmp from the same killed run: must be skipped with a
    # warning, not abort every rerun
    with open(os.path.join(d, "ps-shard-0.npz.tmp999.npz"), "wb") as f:
        f.write(b"PK\x03\x04 torn zip garbage")

    assignment = repartition_checkpoint(d, 2, 2)
    assert set(assignment) == {"w", "b", "e"}
    # every param (and w's slots) is back in a canonical file; tmps gone
    found = {}
    for i in range(2):
        with np.load(os.path.join(d, f"ps-shard-{i}.npz")) as z:
            for key in z.files:
                if key.startswith(("p/", "s/")):
                    found[key] = np.array(z[key])
    assert "p/w" in found and float(found["p/w"][0, 0]) == 3.0
    assert "s/w/acc" in found
    assert not [f for f in os.listdir(d) if f.endswith(".tmp.npz")]


def test_ps_resize_without_restore_fails_fast(master, tmp_path):
    """A resized cluster that was NOT restored must make workers fail
    loudly — re-seeding empty shards from a worker's stale snapshot
    would silently discard other workers' progress."""
    owner = MasterClient(master.addr, node_id=9)
    shards = [
        start_ps_shard(i, master_client=owner, optimizer="adagrad:0.3",
                       num_shards=2)
        for i in range(2)
    ]
    new_shards = []
    mc = MasterClient(master.addr, node_id=0)
    try:
        x, y = _make_problem(seed=3)
        cluster = PsClusterClient.discover(mc, num_shards=2)
        trainer = AsyncPsTrainer(_loss_fn, cluster, master_client=mc,
                                 membership_check_every=1)
        trainer.init_params({"w": np.zeros((8, 1), np.float32),
                             "b": np.zeros((1,), np.float32)})
        trainer.step((x[:64], y[:64]))

        for s in shards:
            s.stop()
        # driver "forgets" repartition+restore: fresh EMPTY shards
        new_shards = [
            start_ps_shard(i, master_client=owner, optimizer="adagrad:0.3",
                           num_shards=3)
            for i in range(3)
        ]
        cur = owner.get_cluster_version("global", "worker", 0)
        owner.update_cluster_version("global", cur + 1, "worker", 0,
                                     expected=cur)
        with pytest.raises(RuntimeError, match="repartition"):
            for _ in range(4):
                trainer.step((x[:64], y[:64]))
    finally:
        for s in shards + new_shards:
            s.stop()
        owner.close()
        mc.close()


def test_ps_migration_restore_and_version_bump(master, tmp_path):
    owner = MasterClient(master.addr, node_id=9)
    ckpt = str(tmp_path / "ps_ckpt")
    shards = [
        start_ps_shard(i, master_client=owner, optimizer="adagrad:0.3",
                       checkpoint_dir=ckpt, num_shards=2)
        for i in range(2)
    ]
    replacement = None
    mc = MasterClient(master.addr, node_id=0)
    try:
        x, y = _make_problem(seed=1)
        cluster = PsClusterClient.discover(mc, num_shards=2)
        trainer = AsyncPsTrainer(_loss_fn, cluster, master_client=mc,
                                 membership_check_every=1)
        trainer.init_params({"w": np.zeros((8, 1), np.float32),
                             "b": np.zeros((1,), np.float32)})
        for i in range(40):
            loss_before = trainer.step((x[:128], y[:128]))
        trainer.checkpoint()

        # migrate shard 0: kill it, restore a replacement from checkpoint,
        # bump the global cluster version (what the master's PS manager does
        # after a migration scale event)
        shards[0].stop()
        replacement = start_ps_shard(0, master_client=owner,
                                     optimizer="adagrad:0.3",
                                     checkpoint_dir=ckpt, restore=True)
        cur = owner.get_cluster_version("global", "worker", 0)
        owner.update_cluster_version("global", cur + 1, "worker", 0,
                                     expected=cur)

        # next steps detect the bump, re-resolve, and keep improving
        for i in range(40):
            loss_after = trainer.step((x[:128], y[:128]))
        assert loss_after <= loss_before, (loss_before, loss_after)
    finally:
        for s in shards[1:]:
            s.stop()
        if replacement is not None:
            replacement.stop()
        owner.close()
        mc.close()
