"""Hide the network (ISSUE 10): chunked double-buffered expert dispatch,
FSDP layer prefetch, and the overlap-aware pricing the optimizer acts on.

Pins, per the acceptance criteria:

  * chunked ``grouped_ep`` (C > 1) matches the single-shard oracle
    EXACTLY fwd+bwd with ``dropped_frac == 0`` and zero recompiles
    across steps — on the 4-way CPU mesh the issue names;
  * the shared ``ops.ring`` ring-all-to-all reproduces
    ``lax.all_to_all`` block for block;
  * ``estimate``'s exposed-comm term is monotone non-increasing in C
    (both directions) with BYTES invariant, and the fsdp-prefetch
    exposure never exceeds the serial pricing;
  * the runtime optimizer enumerates ``dispatch_chunks`` only for a
    ``grouped_ep`` job, chooses a C plan for a comm-bound spec,
    publishes it with unchanged knobs as sentinels, and the worker
    applies it LIVE through the prewarmed program cache with ZERO
    recompiles at the swap (``ElasticTrainer.retune`` gate + the
    master→RPC→plan-hook e2e);
  * G108 fires on the committed serial fixture and stays clean on an
    overlapped schedule;
  * G106 audits the CHUNKED schedule's collective bytes within
    tolerance (the ppermute ring's wire bytes match the one-shot
    all-to-all it replaces, minus the diagonal block).
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.common import comm
from dlrover_tpu.common.config import get_context
from dlrover_tpu.models import llama
from dlrover_tpu.ops.moe import MoEConfig, init_moe_params, moe_ffn
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.planner import (
    DeviceSpec,
    ModelSpec,
    estimate,
    model_spec_from_llama,
    overlap_exposed_comm,
    predicted_collective_bytes,
)
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.trainer.elastic import ElasticTrainer

TESTDATA = os.path.join(os.path.dirname(__file__), "testdata")


@pytest.fixture(autouse=True)
def _telemetry_on():
    ctx = get_context()
    prev = ctx.telemetry_enabled
    ctx.telemetry_enabled = True
    yield
    ctx.telemetry_enabled = prev


# -- the shared ring helper ---------------------------------------------------


class TestRingAllToAll:
    # budget triage (PR 16): the primitive is exercised tier-1 through
    # the grouped_ep dropless/skew tests and the chunked-dispatch
    # oracle; the standalone lax parity check rides slow
    @pytest.mark.slow
    def test_matches_lax_all_to_all_and_differentiates(self):
        """The ppermute-ring decomposition IS an all_to_all: same
        blocks, and its transpose runs the mirrored ring (grads flow).
        """
        from jax.sharding import Mesh, PartitionSpec as P

        from dlrover_tpu.ops.ring import ring_all_to_all
        from dlrover_tpu.ops.shard_compat import (
            get_shard_map,
            shard_map_check_kwargs,
        )

        n = 4
        mesh = Mesh(np.array(jax.devices()[:n]), ("x",))
        shard_map = get_shard_map()
        kw = shard_map_check_kwargs(shard_map)
        x = jnp.asarray(
            np.random.RandomState(0).randn(n, n, 6), jnp.float32
        )  # global [n, n, 6], dim 0 sharded

        def ring_body(xl):
            return ring_all_to_all(xl[0], "x", n)[None]

        def a2a_body(xl):
            from jax import lax

            return lax.all_to_all(xl[0], "x", 0, 0)[None]

        ring_fn = shard_map(ring_body, mesh=mesh, in_specs=P("x"),
                            out_specs=P("x"), **kw)
        a2a_fn = shard_map(a2a_body, mesh=mesh, in_specs=P("x"),
                           out_specs=P("x"), **kw)
        np.testing.assert_array_equal(
            np.asarray(ring_fn(x)), np.asarray(a2a_fn(x))
        )

        g_ring = jax.grad(lambda v: (ring_fn(v) ** 2).sum())(x)
        g_a2a = jax.grad(lambda v: (a2a_fn(v) ** 2).sum())(x)
        np.testing.assert_array_equal(
            np.asarray(g_ring), np.asarray(g_a2a)
        )


# -- chunked grouped_ep vs the oracle (the 4-way CPU mesh) --------------------


class TestChunkedDispatch:
    E = 8
    P = 4  # the 4-way expert submesh the issue names

    def _mesh(self):
        from jax.sharding import Mesh

        return Mesh(np.array(jax.devices()[:self.P]), ("expert",))

    def _params_x(self, d=16, f=32, b=2, s=16):
        rng = np.random.RandomState(0)
        params = init_moe_params(jax.random.PRNGKey(0), d, f, self.E)
        x = jnp.asarray(rng.randn(b, s, d), jnp.float32)
        return params, x

    def _cfg(self, chunks, top_k=2):
        return MoEConfig(num_experts=self.E, top_k=top_k,
                         dispatch="grouped_ep", ep_axes=("expert",),
                         mesh=self._mesh(), dispatch_chunks=chunks)

    def test_fwd_and_grads_match_oracle_c124(self):
        """The acceptance pin: C ∈ {1, 2, 4} all reproduce the
        single-shard einsum oracle exactly, forward AND backward
        (top_k=2 — cross-round queue fill rides the exchanged ranks),
        with nothing dropped — chunking is a pure schedule knob."""
        params, x = self._params_x()  # n = Tl*k = 8*2 = 16 per shard
        oracle = MoEConfig(num_experts=self.E, top_k=2,
                           capacity_factor=float(self.E),
                           eval_capacity_factor=float(self.E),
                           dispatch="einsum")

        def grad_fn(cfg):
            def loss(p, x):
                o, a, m = moe_ffn(p, x, cfg, train=False)
                return (o.astype(jnp.float32) ** 2).sum() + a, m

            # jit: the interpret-mode kernels are traced once instead
            # of re-executed op by op (minutes vs seconds on CPU)
            return jax.jit(jax.value_and_grad(
                loss, argnums=(0, 1), has_aux=True))

        (l_o, _), g_o = grad_fn(oracle)(params, x)
        for chunks in (1, 2, 4):
            (l_c, m_c), g_c = grad_fn(self._cfg(chunks))(params, x)
            assert float(l_c) == pytest.approx(float(l_o), rel=1e-4)
            assert float(m_c["dropped_frac"]) == 0.0
            for lo, lc in zip(jax.tree.leaves(g_o),
                              jax.tree.leaves(g_c)):
                np.testing.assert_allclose(
                    np.asarray(lc), np.asarray(lo),
                    rtol=1e-3, atol=1e-4,
                    err_msg=f"grad mismatch at C={chunks}")

    def test_zero_recompiles_across_steps_chunked(self):
        """Static shapes survive the chunked exchange too: one compile
        serves arbitrary routing, including full skew onto one expert.
        """
        params, x0 = self._params_x()
        cfg = MoEConfig(num_experts=self.E, top_k=2,
                        dispatch="grouped_ep", ep_axes=("expert",),
                        mesh=self._mesh(), kernel_interpret=True,
                        dispatch_chunks=4)

        @jax.jit
        def step(p, x):
            o, a, m = moe_ffn(p, x, cfg, train=False)
            return o.sum() + a, m["dropped_frac"]

        rs = np.random.RandomState(7)
        for i in range(3):
            if i == 2:  # adversarial: skew all tokens onto one expert
                p = dict(params)
                p["router"]["kernel"] = (
                    params["router"]["kernel"].at[:, 0].add(50.0)
                )
                _, dropped = step(p, jnp.asarray(
                    rs.randn(*x0.shape), jnp.float32))
                assert float(dropped) == 0.0
            else:
                step(params, jnp.asarray(
                    rs.randn(*x0.shape), jnp.float32))
        assert step._cache_size() == 1

    def test_indivisible_chunks_degrade_to_serial(self):
        """n % C != 0 must not change the layout mid-trace: the config
        degrades to the one-shot exchange (logged), same numbers."""
        params, x = self._params_x()  # n = 16 per shard

        def run(cfg):
            return jax.jit(lambda p, v: moe_ffn(
                p, v, cfg, train=False))(params, x)

        out1, aux1, _ = run(self._cfg(1))
        out3, aux3, _ = run(self._cfg(3))
        np.testing.assert_array_equal(np.asarray(out1),
                                      np.asarray(out3))
        assert float(aux1) == float(aux3)


# -- overlap-aware pricing ----------------------------------------------------


def _moe_spec(chunks=1, **over):
    base = dict(
        param_count=25_000_000_000, num_layers=32, hidden_size=4096,
        seq_len=8192, global_batch=64, num_experts=64, moe_top_k=2,
        moe_dispatch="grouped_ep", moe_dispatch_chunks=chunks,
    )
    base.update(over)
    return ModelSpec(**base)


class TestOverlapPricing:
    DEV = DeviceSpec(hbm_bytes=95e9)
    MESH = MeshPlan(data=4, fsdp=16)

    def test_exposed_comm_non_increasing_in_chunks_both_ways(self):
        """The acceptance pin: exposed comm is monotone non-increasing
        in C for fixed bytes — checked in both directions, with the
        serial figure invariant (it is the same exchange)."""
        exposed = []
        serial = []
        for c in (1, 2, 4, 8):
            bd = estimate(self.MESH, _moe_spec(c), self.DEV).breakdown
            exposed.append(bd["moe_disp_comm_s"])
            serial.append(bd["moe_disp_comm_serial_s"])
        for a, b in zip(exposed, exposed[1:]):
            assert b <= a
        for a, b in zip(list(reversed(exposed)),
                        list(reversed(exposed))[1:]):
            assert b >= a
        assert exposed[0] == serial[0]  # C=1 IS the serial schedule
        assert len(set(serial)) == 1
        # and the chunked schedule genuinely buys step time here
        assert exposed[-1] < exposed[0]

    def test_bytes_invariant_in_chunks(self):
        """The G106 contract: chunking reshapes the schedule, never the
        traffic — predicted collective bytes identical at every C."""
        b1 = predicted_collective_bytes(self.MESH, _moe_spec(1),
                                        self.DEV)
        b8 = predicted_collective_bytes(self.MESH, _moe_spec(8),
                                        self.DEV)
        assert b1 == b8

    def test_step_time_and_exposed_frac_non_increasing_in_chunks(self):
        scores = [estimate(self.MESH, _moe_spec(c), self.DEV)
                  for c in (1, 2, 4, 8)]
        for a, b in zip(scores, scores[1:]):
            assert b.step_time_s <= a.step_time_s
            assert (b.breakdown["exposed_comm_frac"]
                    <= a.breakdown["exposed_comm_frac"])
        for s in scores:
            assert 0.0 <= s.breakdown["exposed_comm_frac"] <= 1.0

    def test_overlap_formula_edges(self):
        assert overlap_exposed_comm(0.0, 5.0, 8) == 0.0
        assert overlap_exposed_comm(1.0, 5.0, 1) == 1.0
        # fully hideable: only the un-overlappable head remains
        assert overlap_exposed_comm(1.0, 100.0, 4) == pytest.approx(
            0.25)
        # nothing to hide under: the serial cost survives
        assert overlap_exposed_comm(1.0, 0.0, 4) == pytest.approx(1.0)

    def test_fsdp_prefetch_exposes_no_more_than_serial(self):
        spec = dict(param_count=7_000_000_000, num_layers=32,
                    hidden_size=4096, seq_len=4096, global_batch=64)
        off = estimate(MeshPlan(fsdp=32), ModelSpec(**spec), self.DEV)
        on = estimate(MeshPlan(fsdp=32),
                      ModelSpec(fsdp_prefetch=True, **spec), self.DEV)
        assert (on.breakdown["fsdp_comm_s"]
                <= off.breakdown["fsdp_comm_s"])
        assert on.step_time_s <= off.step_time_s
        # the serial twin still shows the pre-overlap figure
        assert (on.breakdown["fsdp_comm_serial_s"]
                == off.breakdown["fsdp_comm_s"])

    def test_llama_spec_resolves_context_chunks(self, monkeypatch):
        cfg = llama.llama_tiny(num_experts=8,
                               moe_dispatch="grouped_ep")
        monkeypatch.setattr(get_context(), "dispatch_chunks", 4)
        assert model_spec_from_llama(cfg, 8).moe_dispatch_chunks == 4
        cfg2 = llama.llama_tiny(num_experts=8,
                                moe_dispatch="grouped_ep",
                                moe_dispatch_chunks=2)
        assert model_spec_from_llama(cfg2, 8).moe_dispatch_chunks == 2


# -- the optimizer's dispatch_chunks knob family ------------------------------


class _Store:
    def __init__(self):
        self._s = {}

    def node_ids(self):
        return list(self._s)

    def latest(self, nid):
        return self._s.get(nid)


class _Snap:
    def __init__(self, step_p50, exposed=None):
        self.ts = time.time()
        self.step_p50 = step_p50
        self.dispatch_p50 = None
        self.exposed_comm_frac = exposed
        self.input_wait_frac = None


def _moe_model_info():
    return comm.ModelInfo(
        num_params=25_000_000_000, hidden_size=4096, num_layers=32,
        seq_len=8192, num_experts=64, moe_top_k=2, ffn_mult=2.7,
    )


def _small_moe_model_info():
    """A spec that FITS the 8-device (2x2x2) CPU mesh under the v5e-ish
    memory gate while staying dispatch-comm-bound, so the chunk family
    wins the wedge's ranking honestly."""
    return comm.ModelInfo(
        num_params=200_000_000, hidden_size=2048, num_layers=16,
        seq_len=4096, num_experts=32, moe_top_k=2, ffn_mult=2.7,
    )


def _running_report(moe_dispatch="grouped_ep", chunks=1):
    return comm.TrainerConfigReport(
        node_id=0, world=64, mesh_shape={"data": 4, "fsdp": 16},
        train_window=4, steps_per_call=1, moe_dispatch=moe_dispatch,
        dispatch_chunks=chunks, global_batch=64,
    )


class TestOptimizerChunkKnob:
    def _opt(self, store, published):
        from dlrover_tpu.master.optimizer import RuntimeOptimizer

        return RuntimeOptimizer(
            store, publish=published.append, mesh_candidates=False,
            device=DeviceSpec(hbm_bytes=95e9), min_speedup=1.02,
        )

    def test_chunk_family_enumerated_only_for_grouped_ep(self):
        store = _Store()
        store._s[0] = _Snap(16.6)
        opt = self._opt(store, [])
        opt.update_model_info(_moe_model_info())
        opt.update_running_config(_running_report("gather"))
        run = opt._running
        _, _, _, _, chunk_opts, _, _ = opt._knob_options(run)
        assert chunk_opts == [1]  # parked off grouped_ep
        opt.update_running_config(_running_report("grouped_ep"))
        _, _, _, _, chunk_opts, _, _ = opt._knob_options(opt._running)
        assert chunk_opts == [1, 2, 4, 8]

    def test_replan_chooses_and_publishes_a_chunk_plan(self):
        """Comm-bound grouped_ep spec → the C family wins the ranking;
        unchanged knobs publish as sentinels so the worker can tell a
        pure chunk swap from a mesh/K change."""
        store = _Store()
        store._s[0] = _Snap(16.6)
        published = []
        opt = self._opt(store, published)
        opt.update_model_info(_moe_model_info())
        opt.update_running_config(_running_report())
        d = opt.replan("test")
        assert d.outcome == "chosen"
        assert d.chosen["dispatch_chunks"] > 1
        assert d.chosen["moe_dispatch"] == "grouped_ep"
        cfg = published[0]
        assert cfg.dispatch_chunks == d.chosen["dispatch_chunks"]
        assert cfg.steps_per_call == 0  # sentinel: unchanged
        assert cfg.train_window == -1
        assert cfg.mesh_shape is None
        assert cfg.moe_dispatch == ""

    def test_exposed_comm_view_pairs_predicted_and_measured(self):
        store = _Store()
        store._s[0] = _Snap(16.6, exposed=0.74)
        store._s[1] = _Snap(16.5, exposed=0.70)
        opt = self._opt(store, [])
        opt.update_model_info(_moe_model_info())
        opt.update_running_config(_running_report(chunks=2))
        view = opt.exposed_comm_view()
        assert 0.0 < view["predicted"] < 1.0
        assert view["measured"] == pytest.approx(0.72)
        assert view["nodes_measured"] == 2
        assert view["dispatch_chunks"] == 2
        # and the plan report carries the pair
        rep = opt.to_report()
        assert rep["exposed_comm"]["measured"] == view["measured"]

    def test_candidate_key_carries_chunks(self):
        """The cooldown/blacklist identity must distinguish chunk
        degrees or a failed C=8 apply would blacklist C=2 too."""
        from dlrover_tpu.master.optimizer.runtime_optimizer import (
            CandidateScore,
        )

        a = CandidateScore(mesh=MeshPlan(data=8), steps_per_call=1,
                           train_window=4, moe_dispatch="grouped_ep",
                           dispatch_chunks=2)
        b = CandidateScore(mesh=MeshPlan(data=8), steps_per_call=1,
                           train_window=4, moe_dispatch="grouped_ep",
                           dispatch_chunks=8)
        assert a.key != b.key


# -- live apply: retune/prewarm through the program cache ---------------------


def _moe_trainer(tmpdir="", chunks=1, **kwargs):
    cfg = llama.llama_tiny(num_experts=8, moe_dispatch="grouped_ep")
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(8, 17))
    batch = {
        "input_ids": jnp.asarray(ids[:, :-1]),
        "labels": jnp.asarray(ids[:, 1:]),
    }
    trainer = ElasticTrainer(
        llama.make_init_fn(cfg),
        llama.make_loss_fn(cfg),
        optax.adafactor(1e-3),
        batch,
        strategy=Strategy(mesh=MeshPlan(data=2, fsdp=2, tensor=2),
                          rule_set="moe_ep"),
        dispatch_chunks=chunks,
        # chunk degree pinned explicitly so the spec does not resolve
        # a stale Context value at build time (see bench.overlap_result)
        model_spec=model_spec_from_llama(
            llama.llama_tiny(num_experts=8, moe_dispatch="grouped_ep",
                             moe_dispatch_chunks=max(1, chunks)), 8),
        **kwargs,
    )
    return trainer, batch


class TestRetuneChunksZeroRecompile:
    # the ~20 s retune e2e is slow-marked per the ISSUE 12 tier-1
    # triage: the prewarm→retune→program-cache mechanics are
    # knob-agnostic and stay tier-1 via PR 7's test_optimizer e2e
    # wedges plus the newest family's gate (test_fsdp_wire
    # TestRetuneFsdpPrecisionZeroRecompile — same cache path, same
    # Context-pin contract); the chunk knob's OWN identity keeps its
    # cheap tier-1 pins (program key, plan-hook routing) below
    @pytest.mark.slow
    def test_prewarmed_chunk_retune_swaps_with_zero_recompiles(self):
        """The acceptance gate: retune() across C values through the
        program cache — a prewarmed chunk degree applies with ZERO
        recompiles, and retuning BACK hits the original program."""
        trainer, batch = _moe_trainer()
        state = trainer.prepare()
        state, m = trainer.step(state, batch)
        assert bool(m["finite"])
        assert trainer.dispatch_chunks == 1

        compiled = trainer.prewarm(dispatch_chunks=2)
        assert compiled  # C=2 is a new program
        assert trainer.dispatch_chunks == 1  # prewarm must not switch

        before = trainer.compile_count
        state = trainer.retune(state, dispatch_chunks=2)
        assert trainer.compile_count == before  # ZERO recompiles
        assert trainer.dispatch_chunks == 2
        assert get_context().dispatch_chunks == 2  # trace knob pinned
        state, m = trainer.step(state, batch)
        assert bool(m["finite"])

        # back to C=1: the startup program is still in the cache
        before = trainer.compile_count
        state = trainer.retune(state, dispatch_chunks=1)
        assert trainer.compile_count == before
        assert trainer.dispatch_chunks == 1
        state, m = trainer.step(state, batch)
        assert bool(m["finite"])

    def test_program_key_distinguishes_chunk_degrees(self):
        trainer, _ = _moe_trainer()
        strategy = trainer._resolved_strategy(8)
        k1 = trainer._program_key(jax.devices(), strategy)
        trainer.dispatch_chunks = 4
        k4 = trainer._program_key(jax.devices(), strategy)
        assert k1 != k4


class TestPlanHookRoutesChunks:
    def test_chunk_plan_reaches_request_retune(self):
        from dlrover_tpu.trainer.executor import OptimizerPlanHook

        class _Ex:
            def __init__(self):
                self.retunes = []

            def request_retune(self, **kw):
                self.retunes.append(kw)

        class _Client:
            def get_parallel_config(self):
                return comm.ParallelConfig(
                    dispatch_chunks=4, plan_id="plan-c4",
                    trace_id="inc-c", predicted_speedup=1.3)

        hook = OptimizerPlanHook(_Client(), poll_secs=0)
        ex = _Ex()
        hook._executor = ex
        hook.poll_once()
        assert ex.retunes[0]["dispatch_chunks"] == 4
        assert ex.retunes[0]["steps_per_call"] is None
        assert ex.retunes[0]["plan_id"] == "plan-c4"


# -- the replan e2e wedge: master → RPC → live chunk apply --------------------


@pytest.mark.slow
class TestChunkReplanWedge:
    """Slow-marked (~80 s; ISSUE 11 budget triage): the closed replan
    loop is tier-1-covered by PR 7's e2e wedges (test_optimizer), and
    the chunk-specific live apply by TestRetuneChunksZeroRecompile +
    the knob/plan-hook unit tests above — the 870 s tier-1 budget on
    this 1-core box cannot carry a ~80 s wedge per knob family."""

    def test_optimizer_selects_chunks_and_worker_applies_live(
            self, tmp_path, monkeypatch):
        """The acceptance wedge: a comm-bound MoE job reports its
        config → the master's optimizer prices the chunk family,
        chooses C > 1, publishes → the worker's plan hook drains and
        applies it through the prewarmed program cache with ZERO
        recompiles at the swap → the ack marks the decision applied."""
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.master.local_master import start_local_master
        from dlrover_tpu.telemetry import EventKind, read_events
        from dlrover_tpu.trainer.conf import Configuration
        from dlrover_tpu.trainer.executor import (
            OptimizerPlanHook,
            TrainExecutor,
            TrainHook,
        )

        events_path = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", events_path)
        ctx = get_context()
        monkeypatch.setattr(ctx, "replan_min_speedup", 1.02)
        # the live apply pins the chosen knobs into the Context (the
        # trace-time contract) — and since ISSUE 11 the plan may carry
        # moe_precision alongside dispatch_chunks; register restores
        # so the chosen values don't leak into later tests' trace-time
        # resolution
        monkeypatch.setattr(ctx, "dispatch_chunks", ctx.dispatch_chunks)
        monkeypatch.setattr(ctx, "moe_precision", ctx.moe_precision)
        master = start_local_master()
        opt = master.servicer.runtime_optimizer
        # the candidate space under test is the chunk family; mesh
        # re-factorizations have their own wedge (test_optimizer)
        opt._mesh_candidates = False
        opt._device = DeviceSpec(hbm_bytes=95e9)
        try:
            from dlrover_tpu.trainer.executor import (
                NodeRuntimeReportHook,
            )

            client = MasterClient(master.addr, node_id=0)
            client.report_model_info(_small_moe_model_info())
            trainer, batch = _moe_trainer()
            steps = 24
            ex = TrainExecutor(
                trainer, train_iter_fn=lambda: [batch] * steps,
                hooks=[NodeRuntimeReportHook(client, every_steps=4,
                                             min_interval_s=0)],
                conf=Configuration({
                    "train_steps": steps, "log_every_steps": 0,
                    "train_window": 2, "preemption_grace": False,
                    "plan_poll_secs": 0, "runtime_report_steps": 0,
                }),
            )
            ex._master_client = client
            plan_hook = OptimizerPlanHook(client, poll_secs=0)
            plan_hook._executor = ex

            class _Drive(TrainHook):
                """Trigger the replan once the node series has a
                measured anchor, then poll for the published plan."""

                fired = False

                def after_step(self, step, metrics):
                    if step >= 8 and not _Drive.fired:
                        _Drive.fired = True
                        opt.replan("wedge")
                    if step >= 10 and step % 4 == 2:
                        plan_hook.poll_once()

            ex._hooks.append(_Drive())
            ex.train_and_evaluate()
            client.close()

            decisions = opt.decisions()
            chosen = [d for d in decisions
                      if d["outcome"] == "chosen"]
            assert chosen, decisions
            d = chosen[-1]
            assert d["chosen"]["dispatch_chunks"] > 1
            assert d["applied"], d
            assert trainer.dispatch_chunks == \
                d["chosen"]["dispatch_chunks"]
            done = [r for r in read_events(events_path)
                    if r.get("kind") == EventKind.OPTIMIZER_APPLY_DONE
                    and r.get("plan_id") == d["plan_id"]]
            assert done and done[-1]["recompiled"] == 0, done
            assert done[-1]["dispatch_chunks"] == \
                d["chosen"]["dispatch_chunks"]
        finally:
            master.stop()


# -- the CLI line: predicted vs measured side by side -------------------------


class TestExposedCommCLI:
    def test_plan_and_attribution_print_the_pair(self, capsys):
        from dlrover_tpu.telemetry.cli import _print_exposed_comm

        _print_exposed_comm({
            "predicted": 0.69, "measured": 0.74,
            "nodes_measured": 2, "dispatch_chunks": 4,
        })
        out = capsys.readouterr().out
        assert "predicted=0.69" in out
        assert "measured=0.74" in out
        assert "C=4" in out
        # absent halves render as '-', and an empty view prints nothing
        _print_exposed_comm({"predicted": None, "measured": None,
                             "nodes_measured": 0,
                             "dispatch_chunks": 1})
        assert "predicted=-" in capsys.readouterr().out
        _print_exposed_comm(None)
        assert capsys.readouterr().out == ""


# -- the overlap bench wedge --------------------------------------------------


@pytest.mark.slow
class TestOverlapBenchWedge:
    """Slow-marked (~40 s; ISSUE 11 budget triage): the parity /
    zero-recompile / accounting content is tier-1-pinned by
    TestChunkedDispatch and TestRetuneChunksZeroRecompile; the bench
    plumbing itself is exercised by every `bench.py --mode dispatch`
    run."""

    def test_paired_legs_parity_recompiles_and_accounting(self):
        """The CPU-mesh overlap wedge, in-process (tier-1): paired
        C=1 vs C=4 legs through the real executor — parity (bitwise
        within same-C, allclose across C), zero recompiles after
        warmup, and the exposed-comm accounting recorded per leg. The
        RATIO is recorded, not gated: the overlap win is a hardware
        row, labeled pending the tunnel."""
        import bench

        env_keys = {"BENCH_OVERLAP_STEPS": "12",
                    "BENCH_OVERLAP_PAIRS": "1"}
        saved = {k: os.environ.get(k) for k in env_keys}
        os.environ.update(env_keys)
        try:
            rec = bench.overlap_result()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        assert rec["metric"] == "dispatch_overlap_ratio"
        assert "error" not in rec, rec
        detail = rec["detail"]
        assert detail["params_parity"] is True
        assert detail["recompiles_after_warmup"] == 0
        assert detail["dispatch_chunks"] == 4
        assert rec["pending_hardware"] is True
        frac = detail["exposed_comm_frac"]
        assert frac["off_predicted"] is not None
        assert frac["on_predicted"] is not None


# -- lint: G108 + the chunked G106 audit + prefetch G105 ----------------------


class TestG108SerializedCollective:
    def _fixture(self):
        with open(os.path.join(TESTDATA, "g108_serial.hlo")) as fh:
            return fh.read()

    def test_fires_on_the_committed_serial_fixture(self):
        from dlrover_tpu.analysis.graph_lint import (
            check_serialized_collectives,
        )

        findings = check_serialized_collectives(self._fixture())
        assert len(findings) == 1
        assert findings[0].rule_id == "G108"
        assert "all-gather" in findings[0].message

    def test_clean_when_independent_compute_intervenes(self):
        from dlrover_tpu.analysis.graph_lint import (
            check_serialized_collectives,
        )

        overlapped = self._fixture().replace(
            "ROOT %consume",
            "%other = f32[4194304]{0} fusion(f32[4194304]{0} "
            "%scaled), kind=kLoop\n  ROOT %consume",
        )
        assert check_serialized_collectives(overlapped) == []

    def test_small_collectives_are_ignored(self):
        from dlrover_tpu.analysis.graph_lint import (
            check_serialized_collectives,
        )

        small = self._fixture().replace("4194304", "1024")
        assert check_serialized_collectives(small) == []

    def test_wired_into_the_rule_set(self):
        from dlrover_tpu.analysis.graph_lint import (
            ALL_GRAPH_RULES,
            GRAPH_RULE_DOCS,
        )

        assert "G108" in ALL_GRAPH_RULES
        assert "G108" in GRAPH_RULE_DOCS


class TestChunkedGraphLint:
    # slow-marked per the ISSUE 12 tier-1 triage (~12 s, a full
    # accelerate+compile): the G106 audit machinery stays tier-1 via
    # test_lint_clean + test_fsdp_wire's quantized-program audit, and
    # the chunk bytes-invariance via the planner unit pins; the
    # chunked compile re-proof rides tpulint / the slow lane
    @pytest.mark.slow
    def test_chunked_program_passes_the_audit_and_stays_clean(self):
        """G106 on the CHUNKED schedule: the ppermute ring's measured
        collective bytes stay within tolerance of the same planner
        prediction the one-shot all_to_all audits against — and the
        full rule set (donation G105, serialized G108 included) stays
        clean on the chunked program."""
        from dlrover_tpu.analysis.graph_lint import lint_train_step

        report = lint_train_step(
            llama.llama_tiny(num_experts=8, moe_dispatch="grouped_ep",
                             moe_dispatch_chunks=2),
            label="llama_tiny_moe[grouped_ep,C=2]",
        )
        assert report.findings == [
        ], [f.render() for f in report.findings]
        # the ring actually ran: collective-permute traffic appears
        assert report.measured_bytes.get("collective-permute", 0) > 0


class TestPrefetchLint:
    @pytest.mark.slow  # PR 13 triage: a second lint-compile of the
    # prefetch program — prefetch numerics stay tier-1 via the
    # fsdp-wire prefetch oracle (test_fsdp_wire TestFsdpWireOracle::
    # test_prefetch_path_holds_the_oracle_too) and G105 machinery via
    # test_lint_clean
    def test_prefetch_keeps_donation_and_numerics(self):
        """G105 (donation) must survive the prefetch-restructured scan,
        and the prefetched forward matches the plain one to fp32
        roundoff (the schedule changes, the math does not)."""
        from dlrover_tpu.analysis.graph_lint import lint_train_step

        report = lint_train_step(
            llama.llama_tiny(param_dtype=jnp.bfloat16,
                             compute_dtype=jnp.bfloat16,
                             fsdp_prefetch=True),
            label="llama_tiny[prefetch]",
        )
        assert report.findings == [
        ], [f.render() for f in report.findings]

        cfg_off = llama.llama_tiny()
        cfg_on = llama.llama_tiny(fsdp_prefetch=True)
        params = llama.init(jax.random.PRNGKey(0), cfg_off)
        ids = jnp.asarray(np.random.RandomState(0).randint(
            0, cfg_off.vocab_size, size=(2, 16)))
        out_off, _ = llama.apply(params, ids, cfg_off)
        out_on, _ = llama.apply(params, ids, cfg_on)
        np.testing.assert_allclose(np.asarray(out_on),
                                   np.asarray(out_off),
                                   rtol=1e-5, atol=1e-5)
