"""Recovery-readiness plane: the continuous durability audit, the
priced recovery ladder, and the blast-radius verdict pipeline.

Unit matrix for ``telemetry/readiness.py`` (RungPricer calibration +
pricing, the forensic ``predict_report`` / ``readiness_view``
derivations) and ``master/monitor/readiness.py`` (the sweep's coverage /
staleness / budget verdict cascade, gauge export with retraction, the
flag -> listener -> clear arc under one trace id), plus the
paired-median sweep-overhead gate and the in-process acceptance pin:
kill a replica holder with NO training failure -> DIAG_DURABILITY names
the at-risk owner with coverage evidence before any worker dies, the
optimizer replans under the verdict's trace id, re-replication clears
it, and the live (RPC) and forensic (events) CLI views agree
throughout.
"""

import io
import json
import sys
import time

import jax
import jax.numpy as jnp
import optax
import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.checkpoint import replication as repl
from dlrover_tpu.common.config import get_context
from dlrover_tpu.master.local_master import start_local_master
from dlrover_tpu.master.monitor.readiness import (
    VERDICT_DURABILITY,
    ReadinessAuditor,
)
from dlrover_tpu.master.replication import ReplicaDirectory
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.telemetry import (
    EventKind,
    names as tm,
    process_registry,
    read_events,
)
from dlrover_tpu.telemetry.goodput import derive_goodput
from dlrover_tpu.telemetry.readiness import (
    RUNG_INIT,
    RUNG_LADDER,
    RUNG_LIVE_RESHARD,
    RUNG_PEER_REBUILD,
    RUNG_STORAGE_RESTORE,
    RungPricer,
    cheapest_viable_rung,
    predict_report,
    readiness_view,
)
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.failover import RecoveryDecision, classify_recovery


@pytest.fixture()
def readiness_ctx(monkeypatch, tmp_path):
    """Replica plane on with test pacing (same knob discipline as
    tests/test_replication.py: the Context singleton leaks otherwise)
    plus a per-test event timeline."""
    ctx = get_context()
    saved = {k: getattr(ctx, k) for k in (
        "snapshot_replicas", "peer_restore", "replica_cadence_steps",
        "replica_min_interval_secs", "replica_budget_mb",
        "replica_chunk_kb",
    )}
    ctx.snapshot_replicas = 1
    ctx.peer_restore = True
    ctx.replica_cadence_steps = 2
    ctx.replica_min_interval_secs = 0.0
    ctx.replica_budget_mb = 64.0
    ctx.replica_chunk_kb = 4
    monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE",
                       str(tmp_path / "events.jsonl"))
    yield ctx
    for k, v in saved.items():
        setattr(ctx, k, v)


def _events(tmp_path):
    return read_events(str(tmp_path / "events.jsonl"))


def _run_json_cli(argv):
    """Invoke `tpurun <argv>` capturing stdout as parsed JSON."""
    from dlrover_tpu.trainer.run import main as tpurun

    buf, prev = io.StringIO(), sys.stdout
    sys.stdout = buf
    try:
        rc = tpurun(argv)
    finally:
        sys.stdout = prev
    return rc, json.loads(buf.getvalue())


# -- the pricer ---------------------------------------------------------------


class TestRungPricer:
    def test_priors_before_any_observation(self):
        """An uncalibrated ladder quotes the stated pessimistic priors
        in ladder order — it must never talk the planner OUT of a
        cheaper rung it has no evidence about."""
        table = RungPricer().table(region_bytes=0.0)
        assert list(table) == list(RUNG_LADDER)
        assert table[RUNG_LIVE_RESHARD] == 1.0
        assert table[RUNG_PEER_REBUILD] == 5.0
        assert table[RUNG_STORAGE_RESTORE] == 30.0
        assert table[RUNG_INIT] == 120.0

    def test_push_cycle_calibrates_peer_rebuild(self):
        """One replicator push cycle prices the rebuild transfer term:
        1 MB in 0.5 s -> link_bw 2 MB/s, so a 1 MB dead-node rebuild
        (drain 0) predicts fetch 0.5 s + device_put 1e6/2e9 s."""
        p = RungPricer()
        p.observe_push(1.0e6, 0.5)
        got = p.predict(RUNG_PEER_REBUILD, region_bytes=1.0e6,
                        drain_s=0.0)
        assert got == pytest.approx(0.5005, abs=1e-6)
        # the observation-priced rungs are untouched by the push feed
        assert p.predict(RUNG_STORAGE_RESTORE) == 30.0

    def test_realized_ema_and_correction_clamp(self):
        p = RungPricer()
        p.observe_realized(RUNG_STORAGE_RESTORE, 10.0)
        assert p.predict(RUNG_STORAGE_RESTORE) == pytest.approx(10.0)
        # a stamped predicted-vs-realized pair feeds the multiplicative
        # correction; a wild ratio clamps to [0.1, 10]
        p.observe_realized(RUNG_STORAGE_RESTORE, 10.0,
                           predicted_s=0.001)
        assert p.corr[RUNG_STORAGE_RESTORE] == pytest.approx(10.0)
        p2 = RungPricer()
        p2.observe_realized(RUNG_LIVE_RESHARD, 0.001, predicted_s=50.0)
        assert p2.corr[RUNG_LIVE_RESHARD] == pytest.approx(0.1)

    def test_unknown_rung_raises(self):
        with pytest.raises(ValueError):
            RungPricer().predict("teleport")

    def test_cheapest_viable_rung(self):
        table = {RUNG_LIVE_RESHARD: 1.0, RUNG_PEER_REBUILD: 5.0,
                 RUNG_STORAGE_RESTORE: 30.0, RUNG_INIT: 120.0}
        # non-viable rungs are skipped however cheap
        assert cheapest_viable_rung(
            table, {RUNG_STORAGE_RESTORE: True, RUNG_INIT: True},
        ) == RUNG_STORAGE_RESTORE
        # a calibrated cheaper restart outbids a live rung
        priced = dict(table, **{RUNG_PEER_REBUILD: 0.2})
        assert cheapest_viable_rung(
            priced, {r: True for r in RUNG_LADDER},
        ) == RUNG_PEER_REBUILD
        # ties break toward the ladder's traditional order
        tied = {r: 3.0 for r in RUNG_LADDER}
        assert cheapest_viable_rung(
            tied, {r: True for r in RUNG_LADDER},
        ) == RUNG_LIVE_RESHARD
        assert cheapest_viable_rung(table, {}) is None


# -- the sweep (unit, injected inventories) -----------------------------------


def _directory(nodes):
    d = ReplicaDirectory()
    for n in nodes:
        d.register(**n)
    return d


def _auditor(directory, inventory_fn, cadence=2, replicas=1,
             sweep_secs=3600.0, **kw):
    cell = {"replicas": replicas}
    a = ReadinessAuditor(
        directory, cadence_fn=lambda: cadence,
        replicas_fn=lambda: cell["replicas"],
        inventory_fn=inventory_fn, sweep_secs=sweep_secs, **kw)
    return a, cell


OWNER0 = dict(node_id=0, addr="h0", budget_mb=64.0, snapshot_mb=8.0,
              step=4)
HOLDER9 = dict(node_id=9, addr="h9", budget_mb=64.0, snapshot_mb=0.0,
               step=-1)


class TestSweepVerdicts:
    def test_healthy_coverage_prices_peer_rebuild(self, readiness_ctx,
                                                  tmp_path):
        process_registry().reset()
        d = _directory([OWNER0, HOLDER9])
        inv = {"h9": {"0": {"step": 4, "manifest": {}}}}
        a, _ = _auditor(d, lambda eps: inv)
        report = a.sweep(force=True)
        assert report["posture"] == "ready"
        assert report["at_risk_nodes"] == []
        node0 = report["nodes"]["0"]
        assert node0["owner"] and node0["coverage_ok"]
        assert node0["staleness_steps"] == 0
        assert node0["holders"] == [9]
        # a covered dead owner comes back through peer DRAM, and that
        # is the cheapest viable rung (live_reshard needs NOT owning)
        assert node0["best_rung"] == RUNG_PEER_REBUILD
        assert set(node0["predicted_mttr"]) == set(RUNG_LADDER)
        # coverage gauge: 1 for the healthy owner, labeled by node
        reg = process_registry()
        g = reg.get(tm.READINESS_COVERAGE, labels={"node": "0"})
        assert g is not None and g.value == 1.0
        assert reg.get(tm.REPLICA_ASSIGNED_K).value == 1.0
        assert reg.get(tm.REPLICA_DEGRADED_K).value == 0.0

    def test_store_only_holder_is_never_an_owner(self, readiness_ctx,
                                                 tmp_path):
        """Satellite pin: a ``snapshot_mb=0`` node is a holder, never
        an owner — it appears in the holder-load gauge but NEVER in the
        coverage gauge or the at-risk table, even with an empty
        inventory."""
        process_registry().reset()
        d = _directory([OWNER0, HOLDER9])
        a, _ = _auditor(d, lambda eps: {})
        report = a.sweep(force=True)
        node9 = report["nodes"]["9"]
        assert not node9["owner"] and node9["lender"]
        # only the owner is at risk; the store-only node's best rung is
        # the free one — nothing of the training state lives on it
        assert report["at_risk_nodes"] == ["0"]
        assert node9["best_rung"] == RUNG_LIVE_RESHARD
        reg = process_registry()
        assert reg.get(tm.READINESS_COVERAGE,
                       labels={"node": "9"}) is None
        load = reg.get(tm.REPLICA_HOLDER_LOAD_MB, labels={"node": "9"})
        assert load is not None and load.value > 0

    def test_lend_no_dram_owner_is_audited_but_not_loaded(
            self, readiness_ctx, tmp_path):
        """Satellite pin: a ``budget_mb<0`` node lends no DRAM — it is
        absent from the load/headroom gauges — but its OWN regions are
        still audited for coverage like any owner's."""
        process_registry().reset()
        stingy = dict(node_id=1, addr="h1", budget_mb=-1.0,
                      snapshot_mb=8.0, step=4)
        d = _directory([OWNER0, HOLDER9, stingy])
        inv = {"h9": {"0": {"step": 4, "manifest": {}},
                      "1": {"step": 4, "manifest": {}}}}
        a, _ = _auditor(d, lambda eps: inv)
        report = a.sweep(force=True)
        node1 = report["nodes"]["1"]
        assert node1["owner"] and not node1["lender"]
        assert node1["coverage_ok"]
        assert report["at_risk_nodes"] == []
        reg = process_registry()
        assert reg.get(tm.REPLICA_HOLDER_LOAD_MB,
                       labels={"node": "1"}) is None
        assert reg.get(tm.REPLICA_HOLDER_HEADROOM_MB,
                       labels={"node": "1"}) is None

    def test_coverage_loss_flags_then_clears_under_one_tid(
            self, readiness_ctx, tmp_path):
        process_registry().reset()
        d = _directory([OWNER0, HOLDER9])
        inv = {"h9": {"0": {"step": 4, "manifest": {}}}}
        box = {"inv": inv}
        a, _ = _auditor(d, lambda eps: box["inv"])
        calls = []
        a.add_verdict_listener(lambda n, v: calls.append((n, v)))
        assert a.sweep(force=True)["posture"] == "ready"

        box["inv"] = {}  # the holder's copy is gone
        degraded = a.sweep(force=True)
        assert degraded["posture"] == "degraded"
        assert degraded["at_risk_nodes"] == ["0"]
        assert (0, VERDICT_DURABILITY) in calls
        ev = _events(tmp_path)
        flag = [r for r in ev if r["kind"] == EventKind.DIAG_DURABILITY]
        assert flag and flag[-1]["error_code"] == "DURABILITY_COVERAGE"
        assert flag[-1]["diag_node"] == 0
        assert flag[-1]["required"] == 1 and flag[-1]["held"] == 0
        tid = flag[-1]["trace_id"]
        edge = [r for r in ev
                if r["kind"] == EventKind.READINESS_DEGRADED]
        assert edge and edge[-1]["trace_id"] == tid
        reg = process_registry()
        assert reg.get(tm.READINESS_COVERAGE,
                       labels={"node": "0"}).value == 0.0
        # a steady degraded state refreshes evidence, not the trace id
        a.sweep(force=True)
        assert a.verdicts()[0].trace_id == tid

        box["inv"] = inv  # re-replicated
        cleared = a.sweep(force=True)
        assert cleared["posture"] == "ready"
        assert (0, "healthy") in calls
        ev = _events(tmp_path)
        rec = [r for r in ev
               if r["kind"] == EventKind.DIAG_RECOVERED
               and r.get("was") == VERDICT_DURABILITY]
        assert rec and rec[-1]["trace_id"] == tid
        restored = [r for r in ev
                    if r["kind"] == EventKind.READINESS_RESTORED]
        assert restored and restored[-1]["trace_id"] == tid
        assert reg.get(tm.READINESS_COVERAGE,
                       labels={"node": "0"}).value == 1.0

    def test_staleness_beyond_cadence_budget_flags(self, readiness_ctx,
                                                   tmp_path):
        process_registry().reset()
        old = dict(OWNER0, step=10)
        d = _directory([old, HOLDER9])
        inv = {"h9": {"0": {"step": 2, "manifest": {}}}}
        a, _ = _auditor(d, lambda eps: inv, cadence=2)  # allowed = 4
        report = a.sweep(force=True)
        assert report["at_risk_nodes"] == ["0"]
        ev = _events(tmp_path)
        flag = [r for r in ev if r["kind"] == EventKind.DIAG_DURABILITY]
        assert flag[-1]["error_code"] == "REPLICA_STALE"
        assert flag[-1]["staleness_steps"] == 8
        assert flag[-1]["allowed_steps"] == 4
        g = process_registry().get(tm.READINESS_STALENESS,
                                   labels={"node": "0"})
        assert g is not None and g.value == 8.0

    def test_interval_gate_and_retraction(self, readiness_ctx,
                                          tmp_path):
        process_registry().reset()
        d = _directory([OWNER0, HOLDER9])
        inv = {"h9": {"0": {"step": 4, "manifest": {}}}}
        a, cell = _auditor(d, lambda eps: inv)
        assert a.sweep() is not None       # first tick is due
        assert a.sweep() is None           # interval-gated
        assert a.sweep(force=True) is not None
        # sweep_secs=0 disables the periodic path entirely
        off, _ = _auditor(d, lambda eps: inv, sweep_secs=0.0)
        assert off.sweep() is None
        # turning the plane off retracts the plan-wide scalars —
        # absent-not-zero, never a stale 1
        reg = process_registry()
        assert reg.get(tm.REPLICA_ASSIGNED_K) is not None
        cell["replicas"] = 0
        a.sweep(force=True)
        assert reg.get(tm.REPLICA_ASSIGNED_K) is None
        assert reg.get(tm.REPLICA_DEGRADED_K) is None


# -- sweep overhead gate (paired-median, ISSUE 15 methodology) ----------------


class TestSweepOverheadGate:
    def test_interval_gated_sweep_is_free_on_the_stats_tick(
            self, readiness_ctx, tmp_path):
        """The continuous audit must not tax the master's stats loop:
        an interval-gated ``sweep()`` call (the common, not-due case)
        adds ≤5% over the directory work the tick already does.
        Run-to-run drift on a shared box dwarfs the real cost, so the
        gate compares back-to-back pairs (alternating order), takes
        the median of per-pair ratios, and retries up to 3 attempts
        with best-of-2 legs, gating on the minimum attempt median —
        the tier-1 de-flake pattern the telemetry overhead gate uses."""
        d = _directory([OWNER0, HOLDER9] + [
            dict(node_id=n, addr=f"h{n}", budget_mb=64.0,
                 snapshot_mb=8.0, step=4) for n in (1, 2, 3, 4)
        ])
        a, _ = _auditor(d, lambda eps: {}, sweep_secs=3600.0)
        a.sweep(force=True)  # prime: every later sweep() is gated
        iters = 2000

        def leg(instrumented, best_of=1):
            best = None
            for _ in range(best_of):
                t0 = time.perf_counter()
                for _ in range(iters):
                    d.admitted_replicas(1)
                    if instrumented:
                        a.sweep()
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            return best

        def paired_median(pairs=3, best_of=1):
            ratios = []
            for i in range(pairs):
                if i % 2 == 0:
                    dt_b = leg(False, best_of)
                    dt_i = leg(True, best_of)
                else:
                    dt_i = leg(True, best_of)
                    dt_b = leg(False, best_of)
                ratios.append(dt_i / dt_b)
            return sorted(ratios)[len(ratios) // 2]

        medians = [paired_median()]
        while medians[-1] - 1.0 > 0.05 and len(medians) < 3:
            medians.append(paired_median(best_of=2))
        overhead = min(medians) - 1.0
        assert overhead <= 0.05, (
            f"readiness sweep overhead {overhead:.1%} above the 5% "
            f"budget (attempt medians {[round(m, 3) for m in medians]})"
        )


# -- forensic derivations -----------------------------------------------------


class TestPredictReport:
    def test_stamped_incident_gains_prediction_columns(self):
        t = time.time()
        records = [
            {"kind": "train_start", "ts": t, "pid": 1, "mono": 0.0},
            {"kind": "peer_rebuild_begin", "ts": t + 1, "pid": 1,
             "mono": 1.0, "predicted_mttr_s": 1.5,
             "rung": "peer_rebuild"},
            {"kind": "peer_rebuild_done", "ts": t + 3, "pid": 1,
             "mono": 3.0, "step": 4, "predicted_mttr_s": 1.5,
             "realized_mttr_s": 2.0, "rung": "peer_rebuild"},
        ]
        rep = predict_report(records)
        assert rep["priced"] == 1 and rep["within_2x"] == 1
        (row,) = [r for r in rep["incidents"]
                  if r["scenario"] == "peer_rebuild"]
        assert row["predicted_s"] == 1.5
        assert row["realized_s"] == 2.0
        assert row["rung"] == "peer_rebuild"
        assert row["ratio"] == 0.75

    def test_unstamped_incident_stays_unpriced_not_zero(self):
        t = time.time()
        records = [
            {"kind": "peer_rebuild_begin", "ts": t + 1, "pid": 1,
             "mono": 1.0},
            {"kind": "peer_rebuild_done", "ts": t + 3, "pid": 1,
             "mono": 3.0, "step": 4},
        ]
        rep = predict_report(records)
        assert rep["priced"] == 0 and rep["within_2x"] == 0
        (row,) = rep["incidents"]
        assert row["predicted_s"] is None and row["ratio"] is None


class TestReadinessView:
    def test_replays_verdict_and_posture_edges(self):
        t = time.time()
        records = [
            {"kind": "diag_durability", "ts": t, "diag_node": 0,
             "error_code": "DURABILITY_COVERAGE", "trace_id": "tid-1",
             "required": 1, "held": 0},
            {"kind": "readiness_degraded", "ts": t + 0.01,
             "trace_id": "tid-1", "nodes": [0]},
        ]
        view = readiness_view(records)
        assert view["posture"] == "degraded"
        assert view["at_risk_nodes"] == ["0"]
        assert view["at_risk"]["0"]["error_code"] == \
            "DURABILITY_COVERAGE"
        assert view["at_risk"]["0"]["trace_id"] == "tid-1"
        records += [
            {"kind": "diag_recovered", "ts": t + 5, "diag_node": 0,
             "was": "durability", "trace_id": "tid-1"},
            {"kind": "readiness_restored", "ts": t + 5.01,
             "trace_id": "tid-1"},
        ]
        view = readiness_view(records)
        assert view["posture"] == "ready"
        assert view["at_risk_nodes"] == []

    def test_flag_without_posture_edge_reads_degraded(self):
        """A rotated-away timeline that kept the flag but lost the
        posture edge: the verdict table wins — degraded is the honest
        summary."""
        view = readiness_view([
            {"kind": "diag_durability", "ts": time.time(),
             "diag_node": 2, "error_code": "REPLICA_STALE",
             "trace_id": "t"},
        ])
        assert view["posture"] == "degraded"
        assert view["at_risk_nodes"] == ["2"]


class TestGoodputDurabilityColumn:
    def test_degraded_spell_is_a_column_not_a_bucket(self):
        t = time.time()
        records = [
            {"kind": "train_start", "ts": t, "pid": 1, "mono": 0.0},
            {"kind": "readiness_degraded", "ts": t + 1, "pid": 2,
             "mono": 1.0},
            {"kind": "readiness_restored", "ts": t + 3, "pid": 2,
             "mono": 3.0},
            {"kind": "train_end", "ts": t + 10, "pid": 1,
             "mono": 10.0},
        ]
        ledger = derive_goodput(records)
        col = ledger["detail"]["durability_at_risk"]
        assert col["spells"] == 1
        assert col["seconds"] == pytest.approx(2.0, abs=0.01)

    def test_absent_when_never_at_risk(self):
        t = time.time()
        ledger = derive_goodput([
            {"kind": "train_start", "ts": t, "pid": 1, "mono": 0.0},
            {"kind": "train_end", "ts": t + 5, "pid": 1, "mono": 5.0},
        ])
        assert "durability_at_risk" not in ledger["detail"]


# -- the priced rung choice ---------------------------------------------------


class TestClassifyRecoveryPriced:
    def test_unpriced_table_keeps_the_ladder_order(self):
        assert classify_recovery(EventKind.RDZV_JOIN) == \
            RecoveryDecision.LIVE_RESHARD
        assert classify_recovery(EventKind.RDZV_JOIN, mttr_table={}) \
            == RecoveryDecision.LIVE_RESHARD
        # a table with no live price cannot move the decision
        assert classify_recovery(
            EventKind.RDZV_JOIN,
            mttr_table={RUNG_PEER_REBUILD: 0.1},
        ) == RecoveryDecision.LIVE_RESHARD

    def test_cheaper_restart_rung_outbids_live_reshard(self):
        table = {RUNG_LIVE_RESHARD: 10.0, RUNG_PEER_REBUILD: 1.0,
                 RUNG_STORAGE_RESTORE: 30.0, RUNG_INIT: 120.0}
        assert classify_recovery(EventKind.RDZV_JOIN,
                                 mttr_table=table) == \
            RecoveryDecision.PROCESS_RESTART

    def test_live_stays_when_priced_cheapest(self):
        table = {RUNG_LIVE_RESHARD: 0.5, RUNG_PEER_REBUILD: 5.0,
                 RUNG_STORAGE_RESTORE: 30.0, RUNG_INIT: 120.0}
        assert classify_recovery(EventKind.RDZV_JOIN,
                                 mttr_table=table) == \
            RecoveryDecision.LIVE_RESHARD
        # safety gates still dominate pricing
        cheap_restart = {RUNG_LIVE_RESHARD: 10.0,
                         RUNG_PEER_REBUILD: 1.0}
        assert classify_recovery(
            EventKind.RDZV_JOIN, host_healthy=False,
            mttr_table=cheap_restart,
        ) == RecoveryDecision.POD_RESTART

    def test_dlr008_covers_the_new_failure_kinds(self):
        from dlrover_tpu.analysis.ast_rules import (
            FAILURE_EVENT_ATTRS,
            FAILURE_EVENT_VALUES,
        )

        for attr in ("DIAG_DURABILITY", "READINESS_DEGRADED"):
            assert attr in FAILURE_EVENT_ATTRS
        for val in ("diag_durability", "readiness_degraded"):
            assert val in FAILURE_EVENT_VALUES


# -- acceptance pin: holder kill -> verdict -> replan -> clear ----------------


def _linear_trainer(master, node_id=0):
    def init_fn(rng):
        return {"w": jax.random.normal(rng, (4, 2)), "b": jnp.zeros((2,))}

    def loss_fn(params, batch, rng):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2), {}

    rngs = jax.random.split(jax.random.PRNGKey(0), 2)
    x = jax.random.normal(rngs[0], (16, 4))
    batch = {"x": x, "y": x @ jax.random.normal(rngs[1], (4, 2))}
    trainer = ElasticTrainer(
        init_fn, loss_fn, optax.adam(0.1), batch,
        strategy=Strategy(mesh=MeshPlan(data=-1)),
        master_client=MasterClient(master.addr, node_id=node_id),
        ckpt_dir="",
    )
    return trainer, batch


def _register_holder(master, node_id=9):
    store = repl.ReplicaStore()
    srv, port = repl.start_replica_server(store, host="127.0.0.1")
    client = MasterClient(master.addr, node_id=node_id)
    client.report_replica_endpoint(
        addr=f"127.0.0.1:{port}", budget_mb=64.0, snapshot_mb=0.0,
        step=-1)
    client.close()
    return store, srv


def _push_through_replicator(trainer, state, master, store):
    replicator = repl.SnapshotReplicator(
        trainer._master_client, node_id=0)
    try:
        snap = trainer.snapshot(state)
        assert replicator.submit(snap.tree, snap.meta, snap.step)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if store.inventory().get("0"):
                break
            time.sleep(0.05)
        assert store.inventory().get("0"), "push never landed"
        return snap
    finally:
        replicator.stop()


class TestReadinessEndToEnd:
    def test_holder_kill_flags_owner_before_any_worker_dies(
            self, readiness_ctx, tmp_path):
        """The acceptance pin: kill a replica HOLDER (no training
        failure anywhere) -> the audit names the at-risk OWNER with
        coverage evidence before any worker dies, the optimizer replans
        under the verdict's trace id, re-replication clears it, one
        incident id spans flag -> replan -> clear, and the live (RPC)
        and forensic (events) CLI views agree at every posture."""
        events_path = str(tmp_path / "events.jsonl")
        master = start_local_master()
        try:
            store, srv = _register_holder(master, node_id=9)
            trainer, batch = _linear_trainer(master, node_id=0)
            state = trainer.prepare()
            for _ in range(3):
                state, _ = trainer.step(state, batch)
            _push_through_replicator(trainer, state, master, store)
            seed = MasterClient(master.addr, node_id=0)
            seed.report_trainer_config(
                world=1, mesh_shape={"data": 1}, train_window=4,
                steps_per_call=1, global_batch=8)
            seed.close()

            auditor = master.servicer.readiness_auditor
            ready = auditor.sweep(force=True)
            assert ready["posture"] == "ready", ready["at_risk"]
            node0 = ready["nodes"]["0"]
            assert node0["owner"] and node0["coverage_ok"]
            assert node0["best_rung"] == RUNG_PEER_REBUILD
            # the push cycle calibrated the transfer term: recovery
            # plans now carry real prices, not priors
            assert ready["calibration"]["link_bw_bytes_per_s"]
            plan_client = MasterClient(master.addr, node_id=0)
            plan = plan_client.get_recovery_plan()
            plan_client.close()
            prices = plan["predicted_mttr"]
            assert set(prices) == set(RUNG_LADDER)
            assert 0 < prices[RUNG_PEER_REBUILD] < 5.0

            # kill the HOLDER: nothing about training fails
            srv.stop(grace=0)
            degraded = auditor.sweep(force=True)
            assert degraded["posture"] == "degraded"
            assert degraded["at_risk_nodes"] == ["0"]
            ev = _events(tmp_path)
            assert not any(r["kind"] == EventKind.WORKER_FAILED
                           for r in ev), \
                "the verdict must precede any worker death"
            flag = [r for r in ev
                    if r["kind"] == EventKind.DIAG_DURABILITY]
            assert flag and flag[-1]["diag_node"] == 0
            assert flag[-1]["error_code"] == "DURABILITY_COVERAGE"
            assert flag[-1]["required"] == 1 and flag[-1]["held"] == 0
            tid = flag[-1]["trace_id"]
            # the degradation reached the optimizer under the SAME
            # incident id (verdict listener -> durability:<node> replan)
            opt = [r for r in ev
                   if r["kind"] in (EventKind.OPTIMIZER_REPLAN,
                                    EventKind.OPTIMIZER_PLAN_REJECTED)
                   and r.get("trace_id") == tid]
            assert opt, "no optimizer decision under the verdict tid"

            # live/forensic CLI agreement while degraded
            rc_l, live = _run_json_cli(
                ["readiness", "--addr", master.addr, "--json"])
            rc_f, forensic = _run_json_cli(
                ["readiness", "--events", events_path, "--json"])
            assert rc_l == 0 and rc_f == 0
            assert live["posture"] == forensic["posture"] == "degraded"
            assert live["at_risk_nodes"] == \
                forensic["at_risk_nodes"] == ["0"]

            # re-replication: a fresh holder re-registers as node 9
            # and the owner pushes again
            store2, srv2 = _register_holder(master, node_id=9)
            _push_through_replicator(trainer, state, master, store2)
            cleared = auditor.sweep(force=True)
            assert cleared["posture"] == "ready"
            ev = _events(tmp_path)
            rec = [r for r in ev
                   if r["kind"] == EventKind.DIAG_RECOVERED
                   and r.get("was") == VERDICT_DURABILITY]
            assert rec and rec[-1]["trace_id"] == tid
            restored = [r for r in ev
                        if r["kind"] == EventKind.READINESS_RESTORED]
            assert restored and restored[-1]["trace_id"] == tid

            # agreement holds after the clear too
            rc_l, live = _run_json_cli(
                ["readiness", "--addr", master.addr, "--json"])
            rc_f, forensic = _run_json_cli(
                ["readiness", "--events", events_path, "--json"])
            assert rc_l == 0 and rc_f == 0
            assert live["posture"] == forensic["posture"] == "ready"
            assert live["at_risk_nodes"] == \
                forensic["at_risk_nodes"] == []
            srv2.stop(grace=0)
        finally:
            master.stop()
