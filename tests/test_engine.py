"""Acceleration engine: task protocol over real RPC, multi-client
search, failure handling."""

import threading

import pytest

from dlrover_tpu.parallel.engine import (
    AccelerationEngine,
    EngineClient,
    EngineTask,
    EngineTaskRequest,
    EngineTaskResult,
    TaskType,
)
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.search import StrategyInfo
from dlrover_tpu.parallel.strategy import Strategy


def _candidates():
    return [
        Strategy(mesh=MeshPlan(data=8)),
        Strategy(mesh=MeshPlan(data=4, tensor=2)),
        Strategy(mesh=MeshPlan(data=2, fsdp=2, tensor=2)),
    ]


def _dryrun_fn(strategy: Strategy) -> StrategyInfo:
    # synthetic: tensor parallelism wins
    t = 1.0 / max(strategy.mesh.tensor, 1) + 0.1 * strategy.mesh.data
    return StrategyInfo(strategy=strategy, step_time_s=t)


class TestEngine:
    def test_single_client_search(self):
        engine = AccelerationEngine(_candidates())
        engine.start()
        try:
            client = EngineClient(
                engine.addr, 0, _dryrun_fn, analyse_fn=lambda: {"chips": 8}
            )
            best = client.run()
            assert best.mesh.tensor == 2 and best.mesh.fsdp == 2
            assert engine.servicer.analysis == {"chips": 8}
            assert len(engine.servicer.collection) == 3
            client.close()
        finally:
            engine.stop()

    def test_multi_client_convergence(self):
        engine = AccelerationEngine(_candidates())
        engine.start()
        results = {}

        def worker(rank):
            client = EngineClient(engine.addr, rank, _dryrun_fn,
                                  poll_interval=0.01)
            results[rank] = client.run()
            client.close()

        try:
            threads = [threading.Thread(target=worker, args=(r,))
                       for r in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            # every rank converges on the same winning strategy
            assert len(results) == 3
            meshes = {r.to_json() for r in results.values()}
            assert len(meshes) == 1
        finally:
            engine.stop()

    def test_failed_candidates_excluded(self):
        def flaky_dryrun(strategy):
            if strategy.mesh.data == 8:
                raise MemoryError("oom")
            return _dryrun_fn(strategy)

        engine = AccelerationEngine(_candidates())
        engine.start()
        try:
            best = EngineClient(engine.addr, 0, flaky_dryrun).run()
            assert best.mesh.data != 8
        finally:
            engine.stop()

    def test_all_failing_raises(self):
        def bad(strategy):
            raise RuntimeError("nope")

        engine = AccelerationEngine(_candidates())
        engine.start()
        try:
            with pytest.raises(RuntimeError, match="no viable"):
                EngineClient(engine.addr, 0, bad).run()
        finally:
            engine.stop()

    def test_dead_rank_task_reassigned(self):
        """A rank that takes a DRYRUN and dies must not wedge the
        search: its task times out and is reassigned (engine survives a
        worker loss, reference executor.py:36 task lifecycle)."""
        engine = AccelerationEngine(
            _candidates(), task_timeout_s=0.5, max_attempts=2
        )
        engine.start()
        try:
            # "dead" rank: pulls one dryrun over real RPC, never reports
            dead = EngineClient(engine.addr, 0, _dryrun_fn)
            task = dead._channel.get(EngineTaskRequest(node_rank=0))
            assert task.task_type == TaskType.ANALYSE
            dead._channel.report(EngineTaskResult(task_id=-2, node_rank=0))
            task = dead._channel.get(EngineTaskRequest(node_rank=0))
            assert task.task_type == TaskType.DRYRUN
            dead.close()  # dies mid-dryrun

            # surviving rank completes the search, including the
            # abandoned task after its timeout expires
            survivor = EngineClient(engine.addr, 1, _dryrun_fn,
                                    poll_interval=0.05)
            best = survivor.run()
            assert best.mesh.tensor == 2 and best.mesh.fsdp == 2
            assert len(engine.servicer.collection) == 3
            survivor.close()
        finally:
            engine.stop()

    def test_failure_report_reassigns_without_waiting_timeout(self):
        """Round-2 verdict weak #6: the master knows a rank died within
        seconds — the engine's failure watcher polls the master's
        failure reports (real RPC end to end) and reassigns the dead
        rank's task immediately, no 10-minute timeout stall."""
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.common.constants import TrainingExceptionLevel
        from dlrover_tpu.master.local_master import start_local_master

        master = start_local_master()
        engine = AccelerationEngine(
            _candidates(), task_timeout_s=3600.0, max_attempts=2
        )
        engine.start()
        engine.watch_failures(
            MasterClient(master.addr, node_id=99), poll_secs=0.05
        )
        try:
            dead = EngineClient(engine.addr, 0, _dryrun_fn)
            task = dead._channel.get(EngineTaskRequest(node_rank=0))
            assert task.task_type == TaskType.ANALYSE
            dead._channel.report(EngineTaskResult(task_id=-2, node_rank=0))
            task = dead._channel.get(EngineTaskRequest(node_rank=0))
            assert task.task_type == TaskType.DRYRUN
            dead.close()  # dies mid-dryrun; timeout is 1 h

            # the agent-side failure report reaches the master; the
            # watcher picks it up and frees the wedged task
            MasterClient(master.addr, node_id=0).report_failure(
                node_rank=0, restart_count=0,
                error_data="worker process died",
                level=TrainingExceptionLevel.PROCESS_ERROR,
            )

            survivor = EngineClient(engine.addr, 1, _dryrun_fn,
                                    poll_interval=0.05)
            best = survivor.run()  # would hang behind WAIT otherwise
            assert best is not None
            assert len(engine.servicer.collection) == 3
            survivor.close()
        finally:
            engine.stop()
            master.stop()

    def test_repeatedly_timing_out_task_marked_failed(self):
        """A candidate that never completes within max_attempts is
        excluded instead of blocking FINISH."""
        from dlrover_tpu.parallel.engine import AccelerationEngineServicer

        servicer = AccelerationEngineServicer(
            _candidates(), analyse_first=False,
            task_timeout_s=0.01, max_attempts=2,
        )
        import time

        seen = []
        # drain: every poll abandons the handed-out task; timeouts
        # expire between polls until all candidates exhaust attempts
        for _ in range(20):
            task = servicer.get(EngineTaskRequest(node_rank=0))
            if task.task_type == TaskType.DRYRUN:
                seen.append(task.task_id)
                time.sleep(0.02)  # let it expire
            elif task.task_type in (TaskType.FINISH, TaskType.FAIL):
                break
        # every candidate got exactly max_attempts tries then failed
        assert all(seen.count(t) == 2 for t in set(seen))
        assert task.task_type == TaskType.FAIL  # nothing ever succeeded
        # and the failures are recorded, not lost
        assert len(servicer.collection) == 3
        assert all("timeout" in i.error for i in servicer.collection)

    def test_servicer_rejects_unknown_messages(self):
        engine = AccelerationEngine(_candidates())
        out = engine.servicer.get(EngineTaskRequest(node_rank=0))
        # first task is ANALYSE
        assert out.task_type == TaskType.ANALYSE
        bad = engine.servicer.get(EngineTask())
        assert bad.task_type == TaskType.FAIL

    def test_empty_candidates_rejected(self):
        with pytest.raises(ValueError):
            AccelerationEngine([])
