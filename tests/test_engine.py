"""Acceleration engine: task protocol over real RPC, multi-client
search, failure handling."""

import threading

import pytest

from dlrover_tpu.parallel.engine import (
    AccelerationEngine,
    EngineClient,
    EngineTask,
    EngineTaskRequest,
    TaskType,
)
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.search import StrategyInfo
from dlrover_tpu.parallel.strategy import Strategy


def _candidates():
    return [
        Strategy(mesh=MeshPlan(data=8)),
        Strategy(mesh=MeshPlan(data=4, tensor=2)),
        Strategy(mesh=MeshPlan(data=2, fsdp=2, tensor=2)),
    ]


def _dryrun_fn(strategy: Strategy) -> StrategyInfo:
    # synthetic: tensor parallelism wins
    t = 1.0 / max(strategy.mesh.tensor, 1) + 0.1 * strategy.mesh.data
    return StrategyInfo(strategy=strategy, step_time_s=t)


class TestEngine:
    def test_single_client_search(self):
        engine = AccelerationEngine(_candidates())
        engine.start()
        try:
            client = EngineClient(
                engine.addr, 0, _dryrun_fn, analyse_fn=lambda: {"chips": 8}
            )
            best = client.run()
            assert best.mesh.tensor == 2 and best.mesh.fsdp == 2
            assert engine.servicer.analysis == {"chips": 8}
            assert len(engine.servicer.collection) == 3
            client.close()
        finally:
            engine.stop()

    def test_multi_client_convergence(self):
        engine = AccelerationEngine(_candidates())
        engine.start()
        results = {}

        def worker(rank):
            client = EngineClient(engine.addr, rank, _dryrun_fn,
                                  poll_interval=0.01)
            results[rank] = client.run()
            client.close()

        try:
            threads = [threading.Thread(target=worker, args=(r,))
                       for r in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            # every rank converges on the same winning strategy
            assert len(results) == 3
            meshes = {r.to_json() for r in results.values()}
            assert len(meshes) == 1
        finally:
            engine.stop()

    def test_failed_candidates_excluded(self):
        def flaky_dryrun(strategy):
            if strategy.mesh.data == 8:
                raise MemoryError("oom")
            return _dryrun_fn(strategy)

        engine = AccelerationEngine(_candidates())
        engine.start()
        try:
            best = EngineClient(engine.addr, 0, flaky_dryrun).run()
            assert best.mesh.data != 8
        finally:
            engine.stop()

    def test_all_failing_raises(self):
        def bad(strategy):
            raise RuntimeError("nope")

        engine = AccelerationEngine(_candidates())
        engine.start()
        try:
            with pytest.raises(RuntimeError, match="no viable"):
                EngineClient(engine.addr, 0, bad).run()
        finally:
            engine.stop()

    def test_servicer_rejects_unknown_messages(self):
        engine = AccelerationEngine(_candidates())
        out = engine.servicer.get(EngineTaskRequest(node_rank=0))
        # first task is ANALYSE
        assert out.task_type == TaskType.ANALYSE
        bad = engine.servicer.get(EngineTask())
        assert bad.task_type == TaskType.FAIL

    def test_empty_candidates_rejected(self):
        with pytest.raises(ValueError):
            AccelerationEngine([])
