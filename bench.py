"""Headline benchmark: Llama-family pretraining MFU on the local accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is measured MFU / 0.45 (the BASELINE.json Llama-2-7B MFU
target for v5p-32, applied per-chip here since the harness exposes one
chip; multi-chip scaling is validated separately via __graft_entry__.
dryrun_multichip).

``python bench.py --mode recovery`` instead measures MTTR against the
BASELINE.json <90 s restore target: it trains a worker subprocess with
async Orbax checkpointing + the persistent XLA compile cache, SIGKILLs
it (the injected preemption), restarts it, and reports the wall time
from kill to the first post-restore completed step.

Env knobs:
  BENCH_PLATFORM=cpu     run the benchmark logic on CPU (smoke test).
                         Steers EVERY phase uniformly, including the
                         backend probe the MTTR phase shares with the
                         MFU phase: =cpu skips MTTR entirely (a CPU
                         number must never stand against the TPU
                         target); any other value makes the MTTR probe
                         test that backend, not the default one.
  BENCH_STEPS=N          timed steps (default 20)
  BENCH_RECOVERY_STEPS=N recovery-worker training steps (default 60)
  BENCH_PRESET=tiny|1b|long  model size; "long" = 16k-token context on
                         one chip (full remat + chunked lm head)
  BENCH_SEQ=N            sequence length override
  BENCH_BATCH=N          batch rows for the TPU preset (default 4)
  BENCH_REMAT=policy     per-layer remat policy (default dots_saveable)
  BENCH_FLASH=0|1        Pallas flash kernel on/off (default 1)
  BENCH_BLOCK_Q/K=N      flash kernel tile sizes (default 512/1024)
  BENCH_BLOCK_Q/K_BWD=N  backward-kernel tiles (0 = same as forward)
  BENCH_PACKED=1         pack BENCH_DOC_LEN-token documents per row
                         (segmented fused-mask kernel; attention FLOPs
                         counted per document, honestly)
  BENCH_DOC_LEN=N        packed document length (default 2048)
  BENCH_HEAD_CHUNK=N     fused chunked lm-head loss chunk size (0=off)
  BENCH_RECOVERY_DIR=D   scratch dir for --mode recovery artifacts
  BENCH_RECOVERY_PRESET  model preset for the MTTR bench (default
                         "recovery" = GPT-2-124M-scale)
  BENCH_SKIP_RECOVERY=1  default mode: skip the MTTR phase/MTTR.json
"""

from __future__ import annotations

import json
import re
import os
import sys
import time

_T_PROC_START = time.time()

MFU_TARGET = 0.45

# peak bf16 FLOP/s per chip by device kind (public spec sheets)
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5": 459e12,  # v5p
    "TPU v5p": 459e12,
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v6 lite": 918e12,  # v6e/trillium
    "TPU v6e": 918e12,
    "cpu": 5e11,  # nominal, for smoke runs only
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu")
    # longest prefix wins: "TPU v5 lite" must match its own entry, not
    # the "TPU v5" (v5p) one
    best = ""
    for name in PEAK_FLOPS:
        if kind.lower().startswith(name.lower()) and len(name) > len(best):
            best = name
    if best:
        return PEAK_FLOPS[best]
    return PEAK_FLOPS.get("cpu", 5e11)


def _pick_config(platform: str, preset: str):
    from dlrover_tpu.models import llama
    import jax.numpy as jnp

    if preset == "tiny" or platform == "cpu":
        cfg = llama.llama_tiny(
            num_layers=2, max_seq_len=128,
            use_flash=False,
        )
        return cfg, 4, 128
    seq = int(os.environ.get("BENCH_SEQ", "0"))
    if preset == "recovery":
        # GPT-2-124M-scale llama (a BASELINE.json listed config): the
        # MTTR bench measures the recovery MACHINERY (boot, cached
        # compile, staged restore), so the state must be small enough
        # that host<->device transfer isn't the metric — this harness's
        # tunneled chip moves ~25-45 MB/s, an environment artifact a
        # real v5p host (~10 GB/s PCIe/DMA) doesn't have.
        seq = seq or 1024
        batch = int(os.environ.get("BENCH_BATCH", "8"))
        remat = os.environ.get("BENCH_REMAT", "dots_saveable")
        cfg = llama.llama2_7b(
            max_seq_len=seq,
            param_dtype=jnp.bfloat16,
            compute_dtype=jnp.bfloat16,
            remat_policy=remat,
            use_flash=os.environ.get("BENCH_FLASH", "1") == "1",
            hidden_size=768, intermediate_size=2048, num_layers=12,
            num_heads=12, num_kv_heads=12,
        )
        return cfg, batch, seq
    if preset == "long":
        # long-context single-chip: flash attention + full remat +
        # chunked lm head keep memory linear in sequence length.
        # Tiling from the round-3 sweep (docs/bench_tuning.md):
        # block_q 1024 + head chunk 512 -> 0.469 MFU at 16k (was 0.413)
        seq = seq or 16384
        batch = int(os.environ.get("BENCH_BATCH", "1"))
        remat = os.environ.get("BENCH_REMAT", "full")
        os.environ.setdefault("BENCH_HEAD_CHUNK", "512")
        os.environ.setdefault("BENCH_BLOCK_Q", "1024")
    elif preset == "1b":
        # ~940M-param proxy (round-1 headline model)
        seq = seq or 2048
        batch = int(os.environ.get("BENCH_BATCH", "4"))
        remat = os.environ.get("BENCH_REMAT", "dots_saveable")
    else:
        # default: ~2.7B — the largest llama that fits one 16 GB v5e
        # with bf16 params + adafactor; needs full remat + chunked
        # lm-head at this size (dots_saveable overflows the compiler,
        # remat=none needs 42 GB). Shape knobs are the round-3 sweep
        # winner (docs/bench_tuning.md): batch 16 x seq 1024, head
        # chunk 1024, flash block_q 1024 -> 0.563 MFU (b8 x s2048 with
        # the same tiling measures 0.548).
        seq = seq or 1024
        batch = int(os.environ.get("BENCH_BATCH", "16"))
        remat = os.environ.get("BENCH_REMAT", "full")
        os.environ.setdefault("BENCH_HEAD_CHUNK", "1024")
        os.environ.setdefault("BENCH_BLOCK_Q", "1024")
    if preset in ("1b", "long"):
        # the 16k-token long-context preset keeps the ~940M shape: at
        # seq 16384 the activations, not the params, bound the chip
        shape = dict(hidden_size=2048, intermediate_size=5504,
                     num_layers=16, num_heads=16, num_kv_heads=16)
    else:
        shape = dict(hidden_size=2560, intermediate_size=6912,
                     num_layers=32, num_heads=20, num_kv_heads=20)
    cfg = llama.llama2_7b(
        max_seq_len=seq,
        param_dtype=jnp.bfloat16,
        compute_dtype=jnp.bfloat16,
        remat_policy=remat,
        use_flash=os.environ.get("BENCH_FLASH", "1") == "1",
        flash_block_q=int(os.environ.get("BENCH_BLOCK_Q", "512")),
        flash_block_k=int(os.environ.get("BENCH_BLOCK_K", "1024")),
        flash_block_q_bwd=int(os.environ.get("BENCH_BLOCK_Q_BWD", "0")),
        flash_block_k_bwd=int(os.environ.get("BENCH_BLOCK_K_BWD", "0")),
        **shape,
    )
    return cfg, batch, seq


_PROBE_CACHE = {}


def _probe_once(timeout_s: float):
    """One subprocess backend-init attempt. Returns (platform, err)."""
    import subprocess

    override = os.environ.get("BENCH_PLATFORM", "")
    prog = (
        "import jax\n"
        + (f"jax.config.update('jax_platforms', {override!r})\n"
           if override else "")
        + "print(jax.devices()[0].platform)\n"
    )
    try:
        probe = subprocess.run(
            [sys.executable, "-c", prog],
            capture_output=True, text=True, timeout=timeout_s,
        )
        if probe.returncode == 0:
            return (probe.stdout.strip().splitlines() or [""])[-1], ""
        return "", f"backend init failed: {(probe.stderr or '')[-160:]}"
    except subprocess.TimeoutExpired:
        return "", (f"backend init exceeded {timeout_s:.0f}s "
                    "(accelerator tunnel wedged?)")
    except Exception as e:  # noqa: BLE001
        return "", f"{type(e).__name__}: {e}"[:200]


def _probe_backend(timeout_s: float = 300.0, force: bool = False):
    """Backend init in a SUBPROCESS with a timeout, BEFORE this process
    commits to it. A wedged accelerator tunnel blocks ``jax.devices()``
    indefinitely inside a C call no Python timeout can interrupt — the
    driver must get a JSON error line, not a hung bench. Honors the
    BENCH_PLATFORM override exactly as ``_get_devices`` will apply it.
    A failed attempt is retried ONCE (a fresh subprocess is a fresh
    backend init; transient tunnel hiccups recover, a truly wedged
    server fails twice). Cached: the MTTR phase and the MFU phase share
    one probe; ``force`` re-probes (after a suspected mid-run wedge).
    Returns (platform_name, error) — platform "" on failure."""
    if "result" in _PROBE_CACHE and not force:
        return _PROBE_CACHE["result"]
    if os.environ.get("BENCH_IN_RECOVERY_WORKER") or os.environ.get(
        "BENCH_IN_MFU_WORKER"
    ):
        # workers skip the probe: the recovery worker because the
        # kill-to-first-step window IS the metric, the MFU worker
        # because the supervisor probed already and holds the kill
        # switch (its subprocess timeout) for a mid-run wedge
        return "", ""
    platform, err = _probe_once(timeout_s)
    if err:
        print(f"backend probe failed ({err}); retrying once",
              file=sys.stderr)
        platform, err = _probe_once(timeout_s)
    _PROBE_CACHE["result"] = (platform, err)
    return platform, err


def _last_good(metric: str):
    """Most recent COMMITTED good measurement for ``metric``, with the
    commit that carries it — embedded in error artifacts so a failed
    probe never destroys the provenance chain (a wedged-tunnel error
    record must point at the last verified number, not erase it)."""
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))

    def git(*args):
        out = subprocess.run(
            ["git", "-C", repo, *args], capture_output=True, text=True,
            timeout=30,
        )
        return out.stdout if out.returncode == 0 else ""

    def good(record, sha):
        if not isinstance(record, dict) or record.get("error"):
            return None
        if record.get("metric") != metric or not record.get("value"):
            return None
        return {
            "value": record["value"],
            "unit": record.get("unit", ""),
            "vs_baseline": record.get("vs_baseline", 0.0),
            "commit": sha[:12],
        }

    try:
        if metric == "recovery_mttr_s":
            for sha in git("log", "--format=%H", "--", "MTTR.json").split():
                try:
                    rec = json.loads(git("show", f"{sha}:MTTR.json"))
                except json.JSONDecodeError:
                    continue
                found = good(rec, sha)
                if found:
                    return found
            return None
        # MFU: the driver-written BENCH_r*.json artifacts, newest round
        # first — sorted by the PARSED round number, not the filename
        # (lexicographic order breaks at digit-width changes:
        # BENCH_r100 < BENCH_r99 as strings)
        def round_no(name):
            m = re.search(r"BENCH_r(\d+)", name)
            return int(m.group(1)) if m else -1

        names = sorted(
            (n for n in git("ls-files", "BENCH_r*.json").split()),
            key=round_no, reverse=True,
        )
        for name in names:
            sha = git("log", "-1", "--format=%H", "--", name).strip()
            try:
                rec = json.loads(git("show", f"HEAD:{name}"))
            except json.JSONDecodeError:
                continue
            found = good(rec.get("parsed"), sha or "unknown")
            if found:
                found["artifact"] = name
                return found
        return None
    except Exception:  # noqa: BLE001 — provenance must never sink a run
        return None


def _error_line(metric: str, message: str, unit: str = "") -> dict:
    """Error artifact that PRESERVES the last committed good number."""
    record = {
        "metric": metric, "value": 0.0, "unit": unit,
        "vs_baseline": 0.0, "error": message,
    }
    last = _last_good(metric)
    if last:
        record["last_good"] = last
    return record


def _get_devices(metric: str):
    _, err = _probe_backend()
    if err:
        print(json.dumps(_error_line(metric, err)))
        return None, RuntimeError(err)

    import jax

    platform_override = os.environ.get("BENCH_PLATFORM", "")
    if platform_override:
        jax.config.update("jax_platforms", platform_override)
    try:
        return jax.devices(), None
    except Exception as e:
        print(json.dumps(_error_line(metric, f"no devices: {e}"[:200])))
        return None, e


def _build_train(devices, preset: str):
    """Shared model+accelerate construction for all bench modes."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel.accelerate import accelerate
    from dlrover_tpu.parallel.mesh import MeshPlan
    from dlrover_tpu.parallel.strategy import Strategy

    platform_override = os.environ.get("BENCH_PLATFORM", "")
    platform = devices[0].platform
    config, batch_size, seq_len = _pick_config(
        platform_override or platform, preset
    )
    # batch rows must divide over the (data, fsdp) mesh axes
    batch_size = -(-batch_size // len(devices)) * len(devices)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, config.vocab_size, size=(batch_size, seq_len + 1))
    batch = {
        "input_ids": jnp.asarray(ids[:, :-1]),
        "labels": jnp.asarray(ids[:, 1:]),
    }
    doc_len = 0
    if os.environ.get("BENCH_PACKED", "") == "1":
        # packed-documents long-context training (the production shape
        # of a 16k-token batch): BENCH_DOC_LEN-token documents packed
        # into each row, cross-document attention masked INSIDE the
        # segmented flash kernel's tiles — fully masked tiles are
        # skipped, so attention work scales with doc_len, not seq_len
        doc_len = int(os.environ.get("BENCH_DOC_LEN", "2048"))
        doc_len = max(1, min(doc_len, seq_len))
        seg = (np.arange(seq_len) // doc_len).astype(np.int32)
        seg = np.broadcast_to(seg, (batch_size, seq_len)).copy()
        same_next = np.concatenate(
            [seg[:, :-1] == seg[:, 1:],
             np.zeros((batch_size, 1), bool)], axis=1)
        batch["segment_ids"] = jnp.asarray(seg)
        batch["labels"] = jnp.asarray(
            np.where(same_next, ids[:, 1:], -100))

    n_dev = len(devices)
    head_chunk = int(os.environ.get("BENCH_HEAD_CHUNK", "0"))
    result = accelerate(
        llama.make_init_fn(config),
        llama.make_loss_fn(config, head_chunk=head_chunk),
        optax.adafactor(1e-3),
        batch,
        strategy=Strategy(
            mesh=MeshPlan(data=1, fsdp=n_dev),
            rule_set="llama",
            # the model already applies per-layer remat (config.remat_policy
            # inside the scan); wrapping the loss again would double-remat
            remat_policy="",
        ),
        devices=devices,
    )
    # doc_len: 0 = unpacked; packed mode's effective (clamped) document
    # length — the MFU accounting must use EXACTLY the value the batch
    # was built with, never a second env read that could drift
    return result, batch, config, batch_size, seq_len, doc_len


def _maybe_emit_mttr():
    """Default driver invocation: also measure MTTR and write MTTR.json
    (the machine-verifiable recovery artifact). Runs BEFORE this process
    touches the accelerator — the recovery worker subprocesses need the
    chip to themselves. Opt out with BENCH_SKIP_RECOVERY=1."""
    if os.environ.get("BENCH_SKIP_RECOVERY", "") == "1":
        return
    if os.environ.get("BENCH_PLATFORM", "") == "cpu":
        return  # smoke runs: the MTTR claim is a TPU number
    # the subprocess probe keeps this process off the accelerator (the
    # recovery workers must own it); a CPU-only host must not write a
    # CPU-measured number against the TPU target
    platform, probe_err = _probe_backend()
    def write_mttr(result):
        path = os.environ.get("BENCH_MTTR_PATH", "") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "MTTR.json"
        )
        with open(path, "w") as f:
            f.write(json.dumps(result) + "\n")

    def error_artifact(message):
        return _error_line("recovery_mttr_s", message, unit="s")

    if platform == "cpu":
        return  # CPU-only host: never write a CPU number vs the TPU target
    if not platform:
        # a real-TPU host where the probe failed must not silently keep
        # a stale artifact: say so, loudly and in the artifact
        print(f"MTTR skipped: backend probe failed ({probe_err})",
              file=sys.stderr)
        write_mttr(error_artifact(f"backend probe failed: {probe_err}"))
        return
    try:
        result = recovery_result()
    except Exception as e:  # noqa: BLE001 — MTTR must not sink the MFU run
        result = error_artifact(f"{type(e).__name__}: {e}"[:200])
    write_mttr(result)


def _pin_cpu_isa_for_cache():
    """CPU smoke runs cap the ISA at AVX2 so persistent-cache reloads
    are silent and portable. Must run before the CPU client
    initializes; a no-op for the TPU path."""
    if os.environ.get("BENCH_PLATFORM", "") != "cpu":
        return
    from dlrover_tpu.utils.compile_cache import cap_cpu_isa_for_cache

    cap_cpu_isa_for_cache()


def _mfu_worker(out_path: str) -> int:
    """The actual MFU measurement, run under the supervisor's kill
    switch: a wedged compile (the round-3 tunnel incident) dies with
    this subprocess instead of hanging the whole bench. Writes the
    result line to ``out_path``; the supervisor prints it."""
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    preset = os.environ.get("BENCH_PRESET", "")

    devices, err = _get_devices("llama_pretrain_mfu")
    if devices is None:
        return 1

    if os.environ.get("BENCH_MFU_TEST_HANG"):
        # test-only injected wedge (tests/test_bench_wedge.py): block
        # INSIDE the timed region on an event that never fires. The
        # supervisor-kill contract used to be proven by racing a 3s
        # timeout against real compile time, which a warm persistent
        # compile cache wins — the hang must not depend on how long
        # compilation happens to take.
        import threading

        threading.Event().wait()

    import jax

    from dlrover_tpu.models import llama

    result, batch, config, batch_size, seq_len, doc_len = _build_train(
        devices, preset
    )
    n_dev = len(devices)
    state = result.init_fn(jax.random.PRNGKey(0))
    sharded = result.shard_batch(batch)

    t0 = time.time()
    state, metrics = result.train_step(state, sharded, jax.random.PRNGKey(0))
    # device_get of a value that depends on the whole step is the only
    # reliable sync point: on tunneled platforms block_until_ready can
    # return before the remote executable has finished
    jax.device_get(metrics["loss"])
    jax.block_until_ready(state)
    compile_and_first_step = time.time() - t0

    t0 = time.time()
    for i in range(steps):
        state, metrics = result.train_step(
            state, sharded, jax.random.PRNGKey(i + 1)
        )
    # the state dependency chain makes the last step's loss transitively
    # depend on every timed step
    jax.device_get(metrics["loss"])
    jax.block_until_ready(state)
    step_time = (time.time() - t0) / steps

    tokens_per_step = batch_size * seq_len
    # 6N forward+backward FLOPs per token + causal attention term. With
    # BENCH_PACKED, attention spans only the document (the segmented
    # kernel skips cross-document tiles), so USEFUL attention FLOPs
    # scale with doc_len — counting seq_len would overstate MFU
    attn_span = doc_len or seq_len
    n_params = llama.param_count(config)
    attn_flops_tok = (
        12 * config.num_layers * config.hidden_size * attn_span * 0.5
    )
    flops_per_step = (6.0 * n_params + attn_flops_tok) * tokens_per_step
    achieved = flops_per_step / step_time
    peak = _peak_flops(devices[0]) * n_dev
    mfu = achieved / peak

    result_line = {
        "metric": "llama_pretrain_mfu",
        "value": round(mfu, 4),
        "unit": "mfu",
        "vs_baseline": round(mfu / MFU_TARGET, 4),
        "detail": {
            "device_kind": devices[0].device_kind,
            "n_devices": n_dev,
            "params": n_params,
            "tokens_per_s": round(tokens_per_step / step_time, 1),
            "step_time_s": round(step_time, 4),
            "compile_plus_first_step_s": round(compile_and_first_step, 1),
            "final_loss": float(jax.device_get(metrics["loss"])),
        },
    }
    with open(out_path, "w") as f:
        f.write(json.dumps(result_line) + "\n")
    return 0


def main() -> int:
    """Supervisor: probe (with one retry), then run the measurement in
    a KILLABLE subprocess with a hard timeout; on a timeout or crash,
    re-probe the backend and retry the worker once. Always emits
    exactly one JSON line; error lines embed the last committed good
    measurement (``last_good``) so a wedged tunnel can never erase the
    provenance chain. BENCH_MFU_TIMEOUT (s, default 1800) bounds each
    worker attempt."""
    import subprocess
    import tempfile

    _pin_cpu_isa_for_cache()

    _maybe_emit_mttr()

    metric = "llama_pretrain_mfu"
    platform, err = _probe_backend()
    if err:
        print(json.dumps(_error_line(metric, err)))
        return 1

    timeout = float(os.environ.get("BENCH_MFU_TIMEOUT", "1800"))
    env = dict(os.environ)
    env["BENCH_IN_MFU_WORKER"] = "1"
    errors = []
    with tempfile.TemporaryDirectory(prefix="dlrover_mfu_") as scratch:
        for attempt in (1, 2):
            out_path = os.path.join(scratch, f"result_{attempt}.json")
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--mfu-worker", "--out", out_path]
            # Captured streams (the worker's own failure JSON must not
            # leak onto the supervisor's stdout — main() emits exactly
            # ONE line) via Popen in its OWN session: on timeout the
            # whole process GROUP is killed, so a wedged grandchild
            # holding the pipes cannot block the drain and resurrect
            # the hang this supervisor exists to prevent.
            import signal

            proc = subprocess.Popen(
                cmd, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
                start_new_session=True,
            )
            try:
                out_text, err_text = proc.communicate(timeout=timeout)
                if err_text:
                    print(err_text[-4000:], file=sys.stderr, end="")
                if proc.returncode == 0 and os.path.exists(out_path):
                    with open(out_path) as f:
                        print(f.read().strip())
                    return 0
                worker_said = (out_text or "").strip().splitlines()
                detail = f": {worker_said[-1][:160]}" if worker_said else ""
                errors.append(
                    f"attempt {attempt}: worker exited "
                    f"rc={proc.returncode}{detail}"
                )
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    proc.kill()
                proc.communicate()  # group is dead: pipes are at EOF
                errors.append(
                    f"attempt {attempt}: measurement exceeded "
                    f"{timeout:.0f}s (wedged compile?) — worker killed"
                )
            if attempt == 1:
                # a killed worker may have left the tunnel wedged: a
                # fresh forced probe decides whether a retry can work
                platform, err = _probe_backend(force=True)
                if err:
                    errors.append(f"re-probe failed: {err}")
                    break
    print(json.dumps(_error_line(metric, "; ".join(errors)[:400])))
    return 1


# -- dispatch pipeline mode --------------------------------------------------

# wedge target: window=4 + steps_per_call=8 vs the synchronous loop
DISPATCH_SPEEDUP_TARGET = 1.5


def _params_bitwise_equal(a, b) -> bool:
    """Bit-for-bit pytree equality — the parity comparator every
    paired-leg wedge (dispatch / overlap / precision) shares, so the
    contract cannot drift between them."""
    import jax
    import numpy as np

    leaves_a = jax.tree.leaves(a)
    leaves_b = jax.tree.leaves(b)
    return len(leaves_a) == len(leaves_b) and all(
        np.asarray(x).tobytes() == np.asarray(y).tobytes()
        for x, y in zip(leaves_a, leaves_b)
    )


def _warmup_timer(trainer, warmup: int):
    """The shared timed-region hook: t0 at the dispatch of the first
    post-warmup step; the compiled-cache snapshot there is the
    zero-recompile reference every wedge gates on."""
    from dlrover_tpu.trainer.executor import TrainHook

    class _Timer(TrainHook):
        def __init__(self):
            self.t0 = None
            self.cache_at_t0 = None

        def before_step(self, step):
            if step == warmup + 1 and self.t0 is None:
                self.cache_at_t0 = (
                    trainer.accelerated.compiled_cache_size())
                self.t0 = time.perf_counter()

    return _Timer()


def dispatch_result() -> dict:
    """Measure the async dispatch pipeline on the tiny CPU-mesh model:
    steps/sec for {sync, window=W, window=W + steps_per_call=K} through
    the REAL ``TrainExecutor`` loop (per-step finite check on, so the
    sync mode pays the per-step ``float()`` materialization the lagged
    window exists to remove). Also pins zero recompiles after warmup
    and bitwise-identical final params across all three modes.

    Env: BENCH_DISPATCH_STEPS (timed steps, default 192),
    BENCH_DISPATCH_WINDOW (default 4), BENCH_DISPATCH_SPC (default 8).
    """
    import itertools

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from dlrover_tpu.parallel.mesh import MeshPlan
    from dlrover_tpu.parallel.strategy import Strategy
    from dlrover_tpu.trainer.conf import Configuration
    from dlrover_tpu.trainer.elastic import ElasticTrainer
    from dlrover_tpu.trainer.executor import TrainExecutor, TrainHook

    window = int(os.environ.get("BENCH_DISPATCH_WINDOW", "4"))
    spc = int(os.environ.get("BENCH_DISPATCH_SPC", "8"))
    steps = int(os.environ.get("BENCH_DISPATCH_STEPS", "192"))
    steps = max(spc, steps // spc * spc)  # full multi-step groups only
    warmup = 2 * spc

    hidden = 64
    n_dev = len(jax.devices())

    def init_fn(rng):
        ks = jax.random.split(rng, 2)
        return {"w1": jax.random.normal(ks[0], (16, hidden)) * 0.1,
                "w2": jax.random.normal(ks[1], (hidden, 8)) * 0.1}

    def loss_fn(params, b, rng):
        h = jnp.tanh(b["x"] @ params["w1"])
        return jnp.mean((h @ params["w2"] - b["y"]) ** 2), {}

    ks = jax.random.split(jax.random.PRNGKey(0), 2)
    rows = max(32, n_dev * 4)
    x = jax.random.normal(ks[0], (rows, 16))
    batch = {"x": np.asarray(x),
             "y": np.asarray(jnp.tanh(x @ jax.random.normal(ks[1], (16, 8))))}

    def cache_sizes(trainer):
        return trainer.accelerated.compiled_cache_size()

    def run_mode(mode_window, mode_spc, telemetry=True,
                 mode_steps=None, attribution=True):
        from dlrover_tpu.common.config import get_context

        get_context().telemetry_enabled = telemetry
        # the telemetry A/B arms pin attribution OFF on BOTH sides so
        # the pair isolates exactly the instrumentation cost it was
        # designed to measure (the attribution plane's own ≤5% paired
        # gate lives in tests/test_attribution.py); the wedge legs keep
        # it on, which is where the per-leg mfu/exposed numbers come from
        get_context().attribution_enabled = attribution
        n_steps = steps if mode_steps is None else mode_steps
        trainer = ElasticTrainer(
            init_fn, loss_fn, optax.sgd(0.05), batch,
            strategy=Strategy(mesh=MeshPlan(data=-1)),
            steps_per_call=mode_spc,
        )
        timer = _warmup_timer(trainer, warmup)
        executor = TrainExecutor(
            trainer,
            train_iter_fn=lambda: itertools.repeat(batch),
            hooks=[timer],
            conf=Configuration({
                "train_steps": warmup + n_steps,
                "log_every_steps": 0,
                "check_finite_every_steps": 1,
                "train_window": mode_window,
                "preemption_grace": False,
            }),
        )
        executor.train_and_evaluate()
        dt = time.perf_counter() - timer.t0
        recompiles = cache_sizes(trainer) - timer.cache_at_t0
        params = jax.device_get(executor.state.params)
        return n_steps / dt, recompiles, params

    def attr_gauges(telemetry=True):
        """The leg's derived attribution + data-plane gauges (MFU /
        exposed-comm fraction / input-wait fraction), read right after
        its executor finished; None when telemetry was off (no capture
        ran — absent, not 0)."""
        if not telemetry:
            return {"mfu": None, "exposed_comm_frac": None,
                    "input_wait_frac": None}
        from dlrover_tpu.telemetry import names as tmn
        from dlrover_tpu.telemetry.metrics import process_registry

        reg = process_registry()
        mfu = reg.get(tmn.ATTR_MFU)
        frac = reg.get(tmn.ATTR_EXPOSED_COMM_FRAC)
        wait = reg.get(tmn.INPUT_WAIT_FRAC)
        return {
            # 12 digits: a tiny CPU-mesh model against a datasheet TPU
            # peak is ~1e-9 MFU — 6 digits would floor it to a fake 0
            "mfu": round(mfu.value, 12) if mfu is not None else None,
            "exposed_comm_frac": (round(frac.value, 6)
                                  if frac is not None else None),
            # the input-wait share of the leg's last window: an
            # in-memory list iterator should read ~0 — a meaningful
            # value here flags the BENCH itself as input-bound
            "input_wait_frac": (round(wait.value, 6)
                                if wait is not None else None),
        }

    from dlrover_tpu.common.config import get_context as _get_ctx

    prev_telemetry = _get_ctx().telemetry_enabled
    prev_attribution = _get_ctx().attribution_enabled
    try:
        sync_rate, sync_rc, sync_params = run_mode(0, 1)
        sync_attr = attr_gauges()
        win_rate, win_rc, win_params = run_mode(window, 1)
        win_attr = attr_gauges()
        scan_rate, scan_rc, scan_params = run_mode(window, spc)
        scan_attr = attr_gauges()
        # telemetry overhead wedge: same window+scan loop,
        # instrumentation off (null registry handles, no spans/events)
        # vs on. Back-to-back PAIRS, alternating order, median of
        # per-pair ratios: run-to-run drift on a shared host (±10%)
        # dwarfs the real per-step cost (~1-2µs), and adjacent runs
        # share the drift, so the paired ratio is the only stable
        # estimator at these durations.
        ab_steps = max(steps, int(
            os.environ.get("BENCH_DISPATCH_AB_STEPS", "1536"))
            // spc * spc)
        ab_rcs, pair_ratios, inst_rates, bare_rates = [], [], [], []
        bare_params = inst_params = None
        for i in range(3):
            if i % 2 == 0:
                r_bare, rc_b, bare_params = run_mode(
                    window, spc, telemetry=False, mode_steps=ab_steps,
                    attribution=False)
                r_inst, rc_i, inst_params = run_mode(
                    window, spc, mode_steps=ab_steps,
                    attribution=False)
            else:
                r_inst, rc_i, inst_params = run_mode(
                    window, spc, mode_steps=ab_steps,
                    attribution=False)
                r_bare, rc_b, bare_params = run_mode(
                    window, spc, telemetry=False, mode_steps=ab_steps,
                    attribution=False)
            bare_rates.append(r_bare)
            inst_rates.append(r_inst)
            pair_ratios.append(r_bare / max(r_inst, 1e-9))
            ab_rcs += [rc_b, rc_i]
    finally:
        # the A/B arms toggle the process-wide Context: an exception
        # mid-run must not leave telemetry silently off (in-process
        # callers like tests/test_bench_wedge.py share the singleton)
        _get_ctx().telemetry_enabled = prev_telemetry
        _get_ctx().attribution_enabled = prev_attribution
    scan_best = max(inst_rates)
    bare_best = max(bare_rates)
    median_ratio = sorted(pair_ratios)[len(pair_ratios) // 2]
    telemetry_overhead_pct = round(
        max(0.0, median_ratio - 1.0) * 100.0, 2
    )

    parity = (
        _params_bitwise_equal(sync_params, win_params)
        and _params_bitwise_equal(sync_params, scan_params)
        # telemetry must be observation-only: the bare and instrumented
        # A/B arms (same step count as each other) stay bit-identical
        and _params_bitwise_equal(bare_params, inst_params)
    )
    speedup = scan_rate / max(sync_rate, 1e-9)
    result_line = {
        "metric": "dispatch_pipeline_speedup",
        "value": round(speedup, 3),
        "unit": "x",
        # >= 1 means the window+scan loop met the 1.5x wedge target
        "vs_baseline": round(speedup / DISPATCH_SPEEDUP_TARGET, 3),
        "detail": {
            "sync_steps_per_s": round(sync_rate, 1),
            "window_steps_per_s": round(win_rate, 1),
            "window_scan_steps_per_s": round(scan_rate, 1),
            "window_speedup": round(win_rate / max(sync_rate, 1e-9), 3),
            "train_window": window,
            "steps_per_call": spc,
            "timed_steps": steps,
            "recompiles_after_warmup": (
                sync_rc + win_rc + scan_rc + sum(ab_rcs)
            ),
            "params_bitwise_identical": parity,
            "n_devices": n_dev,
            # instrumented-vs-bare A/B on the SAME loop (telemetry
            # registry + spans + events on vs null handles)
            "telemetry_ab_steps": ab_steps,
            "telemetry_on_steps_per_s": round(scan_best, 1),
            "telemetry_off_steps_per_s": round(bare_best, 1),
            "telemetry_overhead_pct": telemetry_overhead_pct,
            # per-leg performance attribution (derived from the same
            # compiled-program record + measured step times)
            "attribution_per_leg": {
                "sync": sync_attr,
                "window": win_attr,
                "window_scan": scan_attr,
            },
        },
    }
    if not parity:
        result_line["error"] = "final params diverged across modes"
    elif sync_rc + win_rc + scan_rc + sum(ab_rcs):
        result_line["error"] = "recompile inside the timed region"
    elif telemetry_overhead_pct > 5.0:
        result_line["error"] = (
            f"telemetry overhead {telemetry_overhead_pct}% above the "
            f"5% budget"
        )
    return result_line


OVERLAP_CHUNKS = 4


def overlap_result() -> dict:
    """Paired overlap-on/off legs of the CHUNKED grouped_ep dispatch
    (ISSUE 10): the same tiny MoE llama trained through the real
    ``ElasticTrainer``/``TrainExecutor`` loop at ``dispatch_chunks=1``
    (serial one-shot all_to_all) vs ``dispatch_chunks=OVERLAP_CHUNKS``
    (ppermute ring, double-buffered), back-to-back pairs in alternating
    order with the MEDIAN of per-pair ratios (the PR 9 de-flake
    methodology), zero recompiles after warmup, and each leg's measured
    ``exposed_comm_frac`` gauge recorded next to the planner's
    overlap-aware prediction.

    Parity contract: final params are BIT-identical across same-C legs
    (the run is deterministic), and allclose across C — per-row outputs
    are exactly equal, but an expert's weight GRADIENT at C>1 is the
    sum of per-chunk GEMM contributions, a different reduction order
    than the one-shot GEMM's, so training trajectories differ by
    float-reassociation rounding (same class as changing the batch
    microbatching).

    On the CPU mesh XLA has no latency-hiding scheduler to exploit the
    chunked schedule, so the RATIO is reported, not gated — the
    hardware row stays labeled pending the tunnel (ROADMAP item 5
    note). What this leg pins is everything the overlap must not
    break: parity, droplessness, recompiles, and the accounting.

    Env: BENCH_OVERLAP_STEPS (timed steps/leg, default 48),
    BENCH_OVERLAP_PAIRS (default 3), BENCH_OVERLAP_CHUNKS.
    """
    import itertools

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from dlrover_tpu.common.config import get_context
    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel.mesh import MeshPlan
    from dlrover_tpu.parallel.planner import (
        estimate,
        model_spec_from_llama,
    )
    from dlrover_tpu.parallel.strategy import Strategy
    from dlrover_tpu.trainer.conf import Configuration
    from dlrover_tpu.trainer.elastic import ElasticTrainer
    from dlrover_tpu.trainer.executor import TrainExecutor, TrainHook

    steps = int(os.environ.get("BENCH_OVERLAP_STEPS", "48"))
    pairs = int(os.environ.get("BENCH_OVERLAP_PAIRS", "3"))
    chunks = int(os.environ.get("BENCH_OVERLAP_CHUNKS",
                                str(OVERLAP_CHUNKS)))
    warmup = 4
    n_dev = len(jax.devices())

    cfg = llama.llama_tiny(num_experts=8, moe_dispatch="grouped_ep")
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(8, 17))
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    mesh = (MeshPlan(data=2, fsdp=2, tensor=2) if n_dev >= 8
            else MeshPlan(data=1, fsdp=max(1, n_dev)))

    def run_leg(c):
        trainer = ElasticTrainer(
            llama.make_init_fn(cfg),
            llama.make_loss_fn(cfg),
            optax.adafactor(1e-3),
            batch,
            strategy=Strategy(mesh=mesh, rule_set="moe_ep"),
            dispatch_chunks=c,
            # wire precision pinned too: a live precision retune earlier
            # in the process (the replan wedge) leaves the Context knob
            # at its chosen value, and an implicit resolve here would
            # silently run the overlap legs on the fp8 wire
            moe_precision="bf16",
            # chunk degree pinned EXPLICITLY into the spec: a 0 here
            # would resolve the Context knob at spec-build time — the
            # PREVIOUS leg's value, since the trainer pins Context only
            # inside _build — and the attribution record would price
            # the wrong schedule
            model_spec=model_spec_from_llama(
                llama.llama_tiny(num_experts=8,
                                 moe_dispatch="grouped_ep",
                                 moe_dispatch_chunks=c),
                ids.shape[0]),
        )
        timer = _warmup_timer(trainer, warmup)
        executor = TrainExecutor(
            trainer,
            train_iter_fn=lambda: itertools.repeat(batch),
            hooks=[timer],
            conf=Configuration({
                "train_steps": warmup + steps,
                "log_every_steps": 0,
                "train_window": 2,
                "preemption_grace": False,
            }),
        )
        executor.train_and_evaluate()
        dt = time.perf_counter() - timer.t0
        recompiles = (trainer.accelerated.compiled_cache_size()
                      - timer.cache_at_t0)
        from dlrover_tpu.telemetry import names as tmn
        from dlrover_tpu.telemetry.metrics import process_registry

        frac = process_registry().get(tmn.ATTR_EXPOSED_COMM_FRAC)
        params = jax.device_get(executor.state.params)
        return {
            "rate": steps / dt,
            "recompiles": recompiles,
            "params": params,
            "exposed_comm_frac": (round(frac.value, 6)
                                  if frac is not None else None),
        }

    prev_telemetry = get_context().telemetry_enabled
    get_context().telemetry_enabled = True
    legs_on, legs_off, ratios, recompiles = [], [], [], 0
    try:
        for i in range(pairs):
            order = ((1, chunks) if i % 2 == 0 else (chunks, 1))
            res = {c: run_leg(c) for c in order}
            legs_off.append(res[1])
            legs_on.append(res[chunks])
            ratios.append(res[chunks]["rate"]
                          / max(res[1]["rate"], 1e-9))
            recompiles += res[1]["recompiles"] + res[chunks][
                "recompiles"]
    finally:
        get_context().telemetry_enabled = prev_telemetry

    def close(a, b):
        return all(
            np.allclose(np.asarray(x), np.asarray(y),
                        rtol=1e-4, atol=1e-5)
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
        )

    parity = (
        all(_params_bitwise_equal(legs_off[0]["params"], leg["params"])
            for leg in legs_off[1:])
        and all(_params_bitwise_equal(legs_on[0]["params"], leg["params"])
                for leg in legs_on[1:])
        and close(legs_off[0]["params"], legs_on[0]["params"])
    )
    median_ratio = sorted(ratios)[len(ratios) // 2]
    # the planner's overlap-aware prediction for both legs, so the
    # artifact carries predicted-vs-measured exposure side by side
    spec1 = model_spec_from_llama(
        llama.llama_tiny(num_experts=8, moe_dispatch="grouped_ep",
                         moe_dispatch_chunks=1), ids.shape[0])
    specC = model_spec_from_llama(
        llama.llama_tiny(num_experts=8, moe_dispatch="grouped_ep",
                         moe_dispatch_chunks=chunks), ids.shape[0])
    resolved = mesh.resolve(n_dev)
    pred_off = estimate(resolved, spec1).breakdown["exposed_comm_frac"]
    pred_on = estimate(resolved, specC).breakdown["exposed_comm_frac"]
    result_line = {
        "metric": "dispatch_overlap_ratio",
        "value": round(median_ratio, 3),
        "unit": "x",
        # CPU mesh: the ratio is recorded, not gated — XLA's CPU
        # backend schedules serially, so the overlap win is a
        # HARDWARE row, labeled pending the tunnel (ROADMAP item 5)
        "vs_baseline": None,
        "platform": "cpu",
        "pending_hardware": True,
        "detail": {
            "dispatch_chunks": chunks,
            "timed_steps_per_leg": steps,
            "pairs": pairs,
            "pair_ratios": [round(r, 3) for r in ratios],
            "overlap_off_steps_per_s": round(
                max(leg["rate"] for leg in legs_off), 2),
            "overlap_on_steps_per_s": round(
                max(leg["rate"] for leg in legs_on), 2),
            "recompiles_after_warmup": recompiles,
            # bitwise within same-C legs; allclose across C (the
            # chunked expert-weight grad is a different reduction
            # order — see the docstring's parity contract)
            "params_parity": parity,
            "n_devices": n_dev,
            "exposed_comm_frac": {
                "off_measured": legs_off[-1]["exposed_comm_frac"],
                "on_measured": legs_on[-1]["exposed_comm_frac"],
                "off_predicted": round(pred_off, 6),
                "on_predicted": round(pred_on, 6),
            },
        },
    }
    if not parity:
        result_line["error"] = (
            "final params diverged between chunked and serial legs"
        )
    elif recompiles:
        result_line["error"] = "recompile inside the timed region"
    return result_line


def precision_result() -> dict:
    """Paired bf16-vs-fp8 legs of the grouped_ep MoE wire (ISSUE 11):
    the same tiny MoE llama trained through the real ``ElasticTrainer``
    / ``TrainExecutor`` loop with ``moe_precision="bf16"`` vs ``"fp8"``
    (block-scaled e4m3 values + f32 per-block scales on every row
    exchange, forward and backward), back-to-back pairs in alternating
    order with the MEDIAN of per-pair ratios, zero recompiles after
    warmup — plus ONE ``fp8_qdq`` reference leg whose final params
    must be BIT-identical to the fp8 leg's (the dequant-exact parity
    contract: quantization commutes with the row exchange, so the
    quantized wire changes transport, never numbers).

    The accounting the artifact carries: each leg's measured
    all-to-all row bytes from the attribution record (the same
    ``collective_bytes_by_kind`` counter the G106 audit reads) beside
    the planner's dtype-aware prediction
    (``predicted_collective_bytes`` moe_dispatch, fp8/bf16 = 0.5625
    with the 32-channel scale side-band included) — the wire-bytes
    halving is verified on the COMPILED program, not asserted from the
    formula.

    On the CPU mesh the exchanges are memcpys, so the steps/sec RATIO
    is recorded, not gated — the fp8 win is a hardware row, labeled
    pending the tunnel (ROADMAP item 5). Env: BENCH_PRECISION_STEPS
    (timed steps/leg, default 48), BENCH_PRECISION_PAIRS (default 3).
    """
    import itertools

    import jax
    import numpy as np
    import optax

    from dlrover_tpu.common.config import get_context
    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel.mesh import MeshPlan
    from dlrover_tpu.parallel.planner import (
        model_spec_from_llama,
        predicted_collective_bytes,
    )
    from dlrover_tpu.parallel.strategy import Strategy
    from dlrover_tpu.trainer.conf import Configuration
    from dlrover_tpu.trainer.elastic import ElasticTrainer
    from dlrover_tpu.trainer.executor import TrainExecutor, TrainHook

    steps = int(os.environ.get("BENCH_PRECISION_STEPS", "48"))
    pairs = int(os.environ.get("BENCH_PRECISION_PAIRS", "3"))
    warmup = 4
    n_dev = len(jax.devices())

    cfg = llama.llama_tiny(num_experts=8, moe_dispatch="grouped_ep")
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(8, 17))
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    mesh = (MeshPlan(data=2, fsdp=2, tensor=2) if n_dev >= 8
            else MeshPlan(data=1, fsdp=max(1, n_dev)))

    def spec_at(precision):
        return model_spec_from_llama(
            llama.llama_tiny(num_experts=8, moe_dispatch="grouped_ep",
                             moe_precision=precision),
            ids.shape[0])

    def run_leg(precision):
        trainer = ElasticTrainer(
            llama.make_init_fn(cfg),
            llama.make_loss_fn(cfg),
            optax.adafactor(1e-3),
            batch,
            strategy=Strategy(mesh=mesh, rule_set="moe_ep"),
            moe_precision=precision,
            # chunks pinned to the serial exchange: this wedge isolates
            # the WIRE PRECISION; a leaked Context chunk knob would
            # reroute the rows onto the ppermute ring mid-comparison
            dispatch_chunks=1,
            # precision pinned EXPLICITLY into the spec (the
            # overlap_result Context-staleness lesson applies
            # unchanged)
            model_spec=spec_at(precision),
        )
        timer = _warmup_timer(trainer, warmup)
        executor = TrainExecutor(
            trainer,
            train_iter_fn=lambda: itertools.repeat(batch),
            hooks=[timer],
            conf=Configuration({
                "train_steps": warmup + steps,
                "log_every_steps": 0,
                "train_window": 2,
                "preemption_grace": False,
            }),
        )
        # the wedge must not masquerade: if the fp8 probe failed, the
        # trainer degraded this leg to the bf16 wire (logged) and an
        # artifact labeled fp8 would be fiction — record the EFFECTIVE
        # precision and let the caller error the run
        effective = trainer.moe_precision
        executor.train_and_evaluate()
        dt = time.perf_counter() - timer.t0
        recompiles = (trainer.accelerated.compiled_cache_size()
                      - timer.cache_at_t0)
        record = trainer.attribution()
        row_bytes = None
        if record is not None:
            # the G106 counter: exchange traffic of the compiled
            # program (all_to_all at C=1; the ring would show up as
            # collective-permute), per device per step
            cb = record.collective_bytes or {}
            row_bytes = (cb.get("all-to-all", 0.0)
                         + cb.get("collective-permute", 0.0))
        params = jax.device_get(executor.state.params)
        return {
            "rate": steps / dt,
            "recompiles": recompiles,
            "params": params,
            "measured_row_bytes": row_bytes,
            "degraded": effective != precision,
        }

    prev_telemetry = get_context().telemetry_enabled
    get_context().telemetry_enabled = True
    legs_q, legs_b, ratios, recompiles = [], [], [], 0
    try:
        for i in range(pairs):
            order = (("bf16", "fp8") if i % 2 == 0
                     else ("fp8", "bf16"))
            res = {p: run_leg(p) for p in order}
            legs_b.append(res["bf16"])
            legs_q.append(res["fp8"])
            ratios.append(res["fp8"]["rate"]
                          / max(res["bf16"]["rate"], 1e-9))
            recompiles += (res["bf16"]["recompiles"]
                           + res["fp8"]["recompiles"])
        # the dequant-exact parity leg: the qdq reference (full-
        # precision wire, identical quantize->dequantize math) must
        # land on BIT-identical final params
        ref_leg = run_leg("fp8_qdq")
    finally:
        get_context().telemetry_enabled = prev_telemetry

    parity = (
        all(_params_bitwise_equal(legs_b[0]["params"], leg["params"])
            for leg in legs_b[1:])
        and all(_params_bitwise_equal(legs_q[0]["params"], leg["params"])
                for leg in legs_q[1:])
        and _params_bitwise_equal(legs_q[0]["params"], ref_leg["params"])
    )
    median_ratio = sorted(ratios)[len(ratios) // 2]
    resolved = mesh.resolve(n_dev)
    pred_b = predicted_collective_bytes(
        resolved, spec_at("bf16"))["moe_dispatch"]
    pred_q = predicted_collective_bytes(
        resolved, spec_at("fp8"))["moe_dispatch"]
    mb = legs_b[-1]["measured_row_bytes"]
    mq = legs_q[-1]["measured_row_bytes"]
    measured_ratio = (mq / mb) if (mb and mq) else None
    result_line = {
        "metric": "moe_wire_precision_ratio",
        "value": round(median_ratio, 3),
        "unit": "x",
        # CPU mesh: exchanges are local memcpys, so halving their
        # bytes buys ~nothing here — the speed ratio is recorded, NOT
        # gated; the fp8 win is a hardware row pending the tunnel
        "vs_baseline": None,
        "platform": "cpu",
        "pending_hardware": True,
        "detail": {
            "moe_precision": "fp8",
            "timed_steps_per_leg": steps,
            "pairs": pairs,
            "pair_ratios": [round(r, 3) for r in ratios],
            "bf16_steps_per_s": round(
                max(leg["rate"] for leg in legs_b), 2),
            "fp8_steps_per_s": round(
                max(leg["rate"] for leg in legs_q), 2),
            "recompiles_after_warmup": recompiles,
            # bitwise within same-precision legs AND fp8 == fp8_qdq
            # (the dequant-exact contract); fp8-vs-bf16 params are NOT
            # compared — quantization legitimately changes the numbers
            # (G109 bounds that drift)
            "params_parity": parity,
            "n_devices": n_dev,
            "wire_bytes": {
                # the G106 counter's view of each compiled program
                # (per device per step) beside the planner's
                # dtype-aware prediction — both ratios should sit near
                # 0.5625 (1-byte values + f32/32 scale side-band over
                # a 2-byte wire... here f32 tokens, so lower still)
                "bf16_measured": mb,
                "fp8_measured": mq,
                "measured_ratio": (round(measured_ratio, 4)
                                   if measured_ratio else None),
                "bf16_predicted": round(pred_b, 1),
                "fp8_predicted": round(pred_q, 1),
                "predicted_ratio": round(pred_q / pred_b, 4),
            },
        },
    }
    degraded = (ref_leg["degraded"]
                or any(leg["degraded"] for leg in legs_q + legs_b))
    if degraded:
        result_line["error"] = (
            "fp8 probe failed on this backend: legs degraded to the "
            "bf16 wire — no fp8 measurement exists to publish"
        )
    elif not parity:
        result_line["error"] = (
            "final params diverged across same-precision legs or "
            "between fp8 and the qdq reference"
        )
    elif recompiles:
        result_line["error"] = "recompile inside the timed region"
    return result_line


def fsdp_precision_result() -> dict:
    """Paired bf16-vs-fp8 legs of the DENSE FSDP wire (ISSUE 12): the
    same tiny dense llama trained through the real ``ElasticTrainer``
    / ``TrainExecutor`` loop with ``fsdp_precision="bf16"`` vs
    ``"fp8"`` (the per-layer param gathers of the scan-over-layers
    ship block-scaled e4m3 + f32 scales; dequant at consumption,
    gradients straight-through), back-to-back pairs in alternating
    order with the MEDIAN of per-pair ratios, zero recompiles after
    warmup — plus ONE ``fp8_qdq`` reference leg whose final params
    must be BIT-identical to the fp8 leg's (the dequant-exact parity
    contract: quantization commutes with the per-layer slice, so the
    quantized wire changes transport, never numbers).

    The accounting the artifact carries: each leg's measured
    all-gather bytes from the attribution record (the same
    ``collective_bytes_by_kind`` counter the G106 audit reads) beside
    the planner's dtype-aware prediction
    (``predicted_collective_bytes`` fsdp — the gather legs at
    ``fsdp_wire_bytes_per_elem``, the grad reduce-scatter at the param
    dtype).

    On the CPU mesh the gathers are memcpys AND the XLA CPU backend
    legalizes fp8 collectives to f16 transport (e4m3 embeds exactly in
    f16 — the bitwise contract survives; the emulated wire ships
    2 B/elem), so the steps/sec RATIO is recorded, not gated — the
    fp8 win is a hardware row, labeled pending the tunnel (ROADMAP
    item 5). Env: BENCH_FSDP_STEPS (timed steps/leg, default 48),
    BENCH_FSDP_PAIRS (default 3)."""
    import itertools

    import jax
    import numpy as np
    import optax

    from dlrover_tpu.common.config import get_context
    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel.mesh import MeshPlan
    from dlrover_tpu.parallel.planner import (
        model_spec_from_llama,
        predicted_collective_bytes,
    )
    from dlrover_tpu.parallel.strategy import Strategy
    from dlrover_tpu.trainer.conf import Configuration
    from dlrover_tpu.trainer.elastic import ElasticTrainer
    from dlrover_tpu.trainer.executor import TrainExecutor

    steps = int(os.environ.get("BENCH_FSDP_STEPS", "48"))
    pairs = int(os.environ.get("BENCH_FSDP_PAIRS", "3"))
    warmup = 4
    n_dev = len(jax.devices())

    # 4 layers so the stacked layer dim shards over a 4-way fsdp axis
    # (the auto rule replicates indivisible dims — an unsharded stack
    # would have no gather wire to measure)
    cfg = llama.llama_tiny(num_layers=4)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(8, 17))
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    mesh = (MeshPlan(data=2, fsdp=4) if n_dev >= 8
            else MeshPlan(data=1, fsdp=max(1, n_dev)))

    def spec_at(precision):
        return model_spec_from_llama(
            llama.llama_tiny(num_layers=4, fsdp_precision=precision),
            ids.shape[0])

    def run_leg(precision):
        trainer = ElasticTrainer(
            llama.make_init_fn(cfg),
            llama.make_loss_fn(cfg),
            optax.adafactor(1e-3),
            batch,
            strategy=Strategy(mesh=mesh, rule_set="llama"),
            # the knobs this wedge does NOT measure are pinned: the
            # precision goes explicitly into trainer AND spec (the
            # overlap_result Context-staleness lesson), chunks stay
            # serial, grad wire exact
            fsdp_precision=precision,
            dispatch_chunks=1,
            grad_precision="bf16",
            model_spec=spec_at(precision),
        )
        timer = _warmup_timer(trainer, warmup)
        executor = TrainExecutor(
            trainer,
            train_iter_fn=lambda: itertools.repeat(batch),
            hooks=[timer],
            conf=Configuration({
                "train_steps": warmup + steps,
                "log_every_steps": 0,
                "train_window": 2,
                "preemption_grace": False,
            }),
        )
        effective = trainer.fsdp_precision
        executor.train_and_evaluate()
        dt = time.perf_counter() - timer.t0
        recompiles = (trainer.accelerated.compiled_cache_size()
                      - timer.cache_at_t0)
        record = trainer.attribution()
        gather_bytes = None
        if record is not None:
            # the G106 counter: the param-gather wire of the compiled
            # program (per device per step) — the traffic the
            # fsdp_precision knob compresses
            cb = record.collective_bytes or {}
            gather_bytes = cb.get("all-gather", 0.0)
        params = jax.device_get(executor.state.params)
        return {
            "rate": steps / dt,
            "recompiles": recompiles,
            "params": params,
            "measured_gather_bytes": gather_bytes,
            "degraded": effective != precision,
        }

    prev_telemetry = get_context().telemetry_enabled
    get_context().telemetry_enabled = True
    legs_q, legs_b, ratios, recompiles = [], [], [], 0
    try:
        for i in range(pairs):
            order = (("bf16", "fp8") if i % 2 == 0
                     else ("fp8", "bf16"))
            res = {p: run_leg(p) for p in order}
            legs_b.append(res["bf16"])
            legs_q.append(res["fp8"])
            ratios.append(res["fp8"]["rate"]
                          / max(res["bf16"]["rate"], 1e-9))
            recompiles += (res["bf16"]["recompiles"]
                           + res["fp8"]["recompiles"])
        # the dequant-exact parity leg: qdq (full-precision wire,
        # identical quantize->dequantize math) must land on
        # BIT-identical final params to the fp8 legs
        ref_leg = run_leg("fp8_qdq")
    finally:
        get_context().telemetry_enabled = prev_telemetry

    parity = (
        all(_params_bitwise_equal(legs_b[0]["params"], leg["params"])
            for leg in legs_b[1:])
        and all(_params_bitwise_equal(legs_q[0]["params"], leg["params"])
                for leg in legs_q[1:])
        and _params_bitwise_equal(legs_q[0]["params"], ref_leg["params"])
    )
    median_ratio = sorted(ratios)[len(ratios) // 2]
    resolved = mesh.resolve(n_dev)
    pred_b = predicted_collective_bytes(
        resolved, spec_at("bf16"))["fsdp"]
    pred_q = predicted_collective_bytes(
        resolved, spec_at("fp8"))["fsdp"]
    mb = legs_b[-1]["measured_gather_bytes"]
    mq = legs_q[-1]["measured_gather_bytes"]
    measured_ratio = (mq / mb) if (mb and mq) else None
    result_line = {
        "metric": "fsdp_wire_precision_ratio",
        "value": round(median_ratio, 3),
        "unit": "x",
        # CPU mesh: gathers are local memcpys (and fp8 transport is
        # legalized to f16), so compressing them buys ~nothing here —
        # the speed ratio is recorded, NOT gated; the fp8 win is a
        # hardware row pending the tunnel
        "vs_baseline": None,
        "platform": "cpu",
        "pending_hardware": True,
        "detail": {
            "fsdp_precision": "fp8",
            "timed_steps_per_leg": steps,
            "pairs": pairs,
            "pair_ratios": [round(r, 3) for r in ratios],
            "bf16_steps_per_s": round(
                max(leg["rate"] for leg in legs_b), 2),
            "fp8_steps_per_s": round(
                max(leg["rate"] for leg in legs_q), 2),
            "recompiles_after_warmup": recompiles,
            # bitwise within same-precision legs AND fp8 == fp8_qdq
            # (the dequant-exact contract, fwd+bwd); fp8-vs-bf16
            # params are NOT compared — weight qdq legitimately
            # changes the numbers (the G109 fsdp family bounds that)
            "params_parity": parity,
            "n_devices": n_dev,
            "wire_bytes": {
                # measured all-gather bytes of each compiled program
                # beside the planner's dtype-aware fsdp prediction.
                # CPU measured ratio lands near the f16-legalized
                # transport (~0.5x of f32), above the true-fp8
                # predicted gather ratio (~0.28x) — documented in
                # docs/parallelism.md
                "bf16_measured": mb,
                "fp8_measured": mq,
                "measured_ratio": (round(measured_ratio, 4)
                                   if measured_ratio else None),
                "bf16_predicted": round(pred_b, 1),
                "fp8_predicted": round(pred_q, 1),
                "predicted_ratio": round(pred_q / pred_b, 4),
            },
        },
    }
    degraded = (ref_leg["degraded"]
                or any(leg["degraded"] for leg in legs_q + legs_b))
    if degraded:
        result_line["error"] = (
            "fp8 probe failed on this backend: legs degraded to the "
            "bf16 wire — no fp8 measurement exists to publish"
        )
    elif not parity:
        result_line["error"] = (
            "final params diverged across same-precision legs or "
            "between fp8 and the qdq reference"
        )
    elif recompiles:
        result_line["error"] = "recompile inside the timed region"
    return result_line


def dispatch_main() -> int:
    result_line = dispatch_result()
    print(json.dumps(result_line))
    # the bench-trajectory artifact: steps/sec wedge + telemetry
    # overhead, derived from the same run (BENCH_DISPATCH_ARTIFACT=""
    # opts out; any other value overrides the default path)
    artifact = os.environ.get(
        "BENCH_DISPATCH_ARTIFACT",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_r06.json"),
    )
    if artifact:
        with open(artifact, "w") as f:
            f.write(json.dumps(result_line) + "\n")
    # the overlap wedge (chunked grouped_ep dispatch, ISSUE 10) rides
    # the dispatch mode and writes its own artifact
    overlap_line = overlap_result()
    print(json.dumps(overlap_line))
    overlap_artifact = os.environ.get(
        "BENCH_OVERLAP_ARTIFACT",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_r09.json"),
    )
    if overlap_artifact:
        with open(overlap_artifact, "w") as f:
            f.write(json.dumps(overlap_line) + "\n")
    # the low-precision wire wedge (fp8 grouped_ep, ISSUE 11) rides the
    # dispatch mode too and writes its own artifact
    precision_line = precision_result()
    print(json.dumps(precision_line))
    precision_artifact = os.environ.get(
        "BENCH_PRECISION_ARTIFACT",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_r10.json"),
    )
    if precision_artifact:
        with open(precision_artifact, "w") as f:
            f.write(json.dumps(precision_line) + "\n")
    # the dense-wire wedge (fp8 FSDP param gathers, ISSUE 12) rides the
    # dispatch mode too and writes its own artifact
    fsdp_line = fsdp_precision_result()
    print(json.dumps(fsdp_line))
    fsdp_artifact = os.environ.get(
        "BENCH_FSDP_ARTIFACT",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_r11.json"),
    )
    if fsdp_artifact:
        with open(fsdp_artifact, "w") as f:
            f.write(json.dumps(fsdp_line) + "\n")
    return 1 if (result_line.get("error")
                 or overlap_line.get("error")
                 or precision_line.get("error")
                 or fsdp_line.get("error")) else 0


# -- recovery (MTTR) mode ----------------------------------------------------

MTTR_TARGET_S = 90.0


def _recovery_worker(ckpt_dir: str, status_file: str, total_steps: int,
                     save_every: int) -> int:
    """Training worker for the MTTR bench: checkpoints as it goes and
    appends one JSON status line per completed step. Restarting it
    resumes from the latest committed checkpoint (the elastic restore
    path: Orbax reshard-on-load + persistent XLA compile cache)."""
    import threading

    from dlrover_tpu.utils.compile_cache import enable_compile_cache

    _pin_cpu_isa_for_cache()  # fresh process: before the client boots
    enable_compile_cache()  # honors DLROVER_COMPILE_CACHE_DIR

    # Overlap the (slow, possibly tunneled) backend init with pulling the
    # latest checkpoint into the page cache, so the restore that follows
    # build is a DRAM read (SURVEY §7: the <90 s budget forces overlapping
    # device init with restore staging).
    stop_prefetch = threading.Event()

    def _prefetch_checkpoint():
        for root, _dirs, files in os.walk(ckpt_dir):
            for name in files:
                try:
                    with open(os.path.join(root, name), "rb") as fh:
                        while fh.read(1 << 22):
                            if stop_prefetch.is_set():
                                return
                except OSError:
                    pass

    prefetch = threading.Thread(target=_prefetch_checkpoint, daemon=True)
    prefetch.start()

    preset = os.environ.get("BENCH_PRESET", "")
    devices, err = _get_devices("recovery_mttr_s")
    if devices is None:
        return 1

    import jax

    from dlrover_tpu.checkpoint.manager import (
        ElasticCheckpointManager,
        abstract_like,
    )

    # Diagnose the warm path: log WHY a compile missed the persistent
    # cache, and issue a tiny device op concurrently with build+restore.
    # If the accelerator is still being reclaimed from the killed
    # predecessor (tunnel/server-side), the warmup op absorbs that wait
    # where it overlaps useful host work instead of serializing in
    # front of the first training step — and its timing tells us whether
    # the first-step gap is device availability or compilation.
    jax.config.update("jax_explain_cache_misses", True)
    warmup = {}

    def _device_warmup():
        t0 = time.time()
        try:
            import jax.numpy as jnp

            x = jax.jit(
                lambda a: (a @ a).sum()
            )(jnp.ones((256, 256), jnp.bfloat16))
            jax.block_until_ready(x)
        except Exception as e:  # noqa: BLE001 — diagnostic only
            warmup["error"] = str(e)[:200]
        warmup["t_warmup_s"] = round(time.time() - t0, 2)

    warmup_thread = threading.Thread(target=_device_warmup, daemon=True)
    warmup_thread.start()

    t_boot = time.time()
    phases = {"t_devices_s": round(time.time() - _T_PROC_START, 2)}
    result, batch, config, _, _, _ = _build_train(devices, preset)
    sharded = result.shard_batch(batch)
    mgr = ElasticCheckpointManager(ckpt_dir, max_to_keep=2)
    phases["t_build_s"] = round(time.time() - t_boot, 2)

    restored_step = -1
    latest = mgr.latest_step()
    if latest is not None:
        abstract = jax.eval_shape(result.init_fn, jax.random.PRNGKey(0))
        target = abstract_like(abstract, result.state_sharding)
        out = mgr.restore(target)
        state = out["state"]
        restored_step = out["step"]
        start = restored_step + 1
    else:
        state = result.init_fn(jax.random.PRNGKey(0))
        start = 0
    jax.block_until_ready(state)
    stop_prefetch.set()
    phases["t_restore_s"] = round(
        time.time() - t_boot - phases["t_build_s"], 2
    )
    t_join = time.time()
    # bounded: the warmup is diagnostic — if it is STILL blocked after
    # build+restore+30s, the device wait would hit the first step
    # anyway; proceeding keeps the instrumentation from inflating the
    # MTTR it measures beyond that bound
    warmup_thread.join(timeout=30)
    if warmup_thread.is_alive():
        warmup["warmup_pending"] = True
    phases["t_warmup_wait_s"] = round(time.time() - t_join, 2)
    phases.update(warmup)
    from dlrover_tpu.utils.compile_cache import cache_entries, cache_stats

    phases["cache_entries_at_boot"] = cache_entries()

    def emit(record):
        with open(status_file, "a") as f:
            f.write(json.dumps(record) + "\n")
            f.flush()
            os.fsync(f.fileno())

    for step in range(start, total_steps):
        t_step = time.time()
        state, metrics = result.train_step(
            state, sharded, jax.random.PRNGKey(step)
        )
        loss = float(jax.device_get(metrics["loss"]))
        jax.block_until_ready(state)
        phases["t_step_s"] = round(time.time() - t_step, 2)
        if step == start:
            # persistent-cache traffic through the first (compiling)
            # step: a warm same-topology restart shows misses == 0 —
            # the zero-recompile gate of the recovery wedge
            traffic = cache_stats()
            phases["cache_hits"] = traffic["hits"]
            phases["cache_misses"] = traffic["misses"]
        committed = -1
        if step > 0 and step % save_every == 0:
            if mgr.save(step, state, metadata={"step": step}, force=True):
                mgr.wait()  # commit before reporting, so the driver can
                committed = step  # kill knowing a restore point exists
        emit({
            "step": step, "t": time.time(), "loss": loss,
            "restored_from": restored_step, "committed": committed,
            "boot_to_step_s": round(time.time() - t_boot, 2),
            **phases,
        })
    mgr.wait()
    mgr.close()
    return 0


def _wait_status(status_file: str, pred, timeout: float, proc=None):
    """Poll the worker's status file until a line satisfies ``pred``.

    Bails out early (after one final read) if ``proc`` has exited."""
    deadline = time.time() + timeout
    seen = 0
    final_read = False
    while time.time() < deadline:
        if os.path.exists(status_file):
            with open(status_file) as f:
                lines = f.read().splitlines()
            idx = seen
            while idx < len(lines):
                try:
                    rec = json.loads(lines[idx])
                except json.JSONDecodeError:
                    break  # torn write: re-read this line next poll
                idx += 1
                if pred(rec):
                    return rec
            seen = idx
        if final_read:
            return None
        if proc is not None and proc.poll() is not None:
            final_read = True  # one more pass over anything just flushed
            continue
        time.sleep(0.2)
    return None


def recovery_result() -> dict:
    """Kill-and-restore MTTR benchmark (BASELINE: <90 s restore).

    Phase 1 trains + checkpoints (cold compile, cache fills, host-DRAM
    staging mirrors the latest step). The SIGKILL is the injected host
    preemption. Phase 2's wall time from kill to the first *completed*
    post-restore step is the MTTR — it includes process boot, JAX init,
    cached compile, staged Orbax restore, and one full training step.
    Returns the result-line dict (with an "error" key on failure).
    """
    import shutil
    import subprocess
    import tempfile

    # deliberately NOT BENCH_STEPS: the MFU step count must not reshape
    # the recovery phase (phase 1 needs >= save_every + 3 steps to commit)
    total_steps = int(os.environ.get("BENCH_RECOVERY_STEPS", "60"))
    save_every = int(os.environ.get("BENCH_SAVE_EVERY", "5"))
    base = os.environ.get("BENCH_RECOVERY_DIR", "")
    scratch = base or tempfile.mkdtemp(prefix="dlrover_mttr_")
    ckpt_dir = os.path.join(scratch, "ckpt")
    cache_dir = os.path.join(scratch, "xla_cache")
    status_file = os.path.join(scratch, "status.jsonl")
    # a reused BENCH_RECOVERY_DIR must start clean: stale checkpoints or
    # status lines from a prior run would be measured as this run's
    for d in (ckpt_dir, cache_dir):
        shutil.rmtree(d, ignore_errors=True)
        os.makedirs(d, exist_ok=True)
    if os.path.exists(status_file):
        os.remove(status_file)

    env = dict(os.environ)
    env["DLROVER_COMPILE_CACHE_DIR"] = cache_dir
    env["BENCH_IN_RECOVERY_WORKER"] = "1"  # skip the backend-init probe
    # recovery workers use the recovery-sized model unless overridden;
    # drop the caller's MFU shape knobs so e.g. BENCH_SEQ=16384 from a
    # long-context MFU run can't reshape the recovery model
    env["BENCH_PRESET"] = os.environ.get("BENCH_RECOVERY_PRESET",
                                         "recovery")
    if "BENCH_RECOVERY_PRESET" not in os.environ:
        for knob in ("BENCH_SEQ", "BENCH_BATCH", "BENCH_REMAT",
                     "BENCH_FLASH", "BENCH_HEAD_CHUNK", "BENCH_BLOCK_Q",
                     "BENCH_BLOCK_K", "BENCH_BLOCK_Q_BWD",
                     "BENCH_BLOCK_K_BWD", "BENCH_PACKED",
                     "BENCH_DOC_LEN", "BENCH_STEPS"):
            env.pop(knob, None)
    cmd = [
        sys.executable, os.path.abspath(__file__), "--recovery-worker",
        "--ckpt-dir", ckpt_dir, "--status-file", status_file,
        "--total-steps", str(total_steps), "--save-every", str(save_every),
    ]

    timeout = float(os.environ.get("BENCH_RECOVERY_TIMEOUT", "1200"))
    p1 = subprocess.Popen(cmd, env=env)
    # wait for a committed checkpoint + a few more steps of progress
    # (the commit marker only appears on the save line itself, so carry
    # the latest commit across lines)
    last_commit = {"step": -1}
    first_line = {}

    def _committed_and_progressed(r):
        if not first_line:  # boot -> step 0: the true cold-boot time
            first_line.update(r)
        if r["committed"] >= 0:
            last_commit["step"] = max(last_commit["step"], r["committed"])
        return (
            last_commit["step"] >= save_every
            and r["step"] >= last_commit["step"] + 2
        )

    rec = _wait_status(status_file, _committed_and_progressed, timeout,
                       proc=p1)
    if rec is None:
        p1.kill()
        p1.wait()  # reap: a wedged host may retry many times
        if not base:
            shutil.rmtree(scratch, ignore_errors=True)
        # through _error_line so the artifact embeds last_good: a
        # wedged phase-1 must not erase the provenance chain either
        return _error_line(
            "recovery_mttr_s",
            "phase-1 worker never reached a committed checkpoint",
            unit="s",
        )
    cold_boot_s = first_line.get("boot_to_step_s", rec["boot_to_step_s"])

    p1.kill()  # SIGKILL: the injected preemption
    p1.wait()
    t_kill = time.time()

    p2 = subprocess.Popen(cmd, env=env)
    rec2 = _wait_status(
        status_file,
        lambda r: r["t"] > t_kill and r["restored_from"] >= 0,
        timeout,
        proc=p2,
    )
    mttr = (rec2["t"] - t_kill) if rec2 else float("inf")
    p2.kill()
    p2.wait()
    if not base:
        shutil.rmtree(scratch, ignore_errors=True)

    if rec2 is None:
        return _error_line(
            "recovery_mttr_s", "restarted worker never stepped", unit="s"
        )

    result_line = {
        "metric": "recovery_mttr_s",
        "value": round(mttr, 1),
        "unit": "s",
        # >1 = faster than the 90 s BASELINE target
        "vs_baseline": round(MTTR_TARGET_S / mttr, 2),
        "detail": {
            "restored_from_step": rec2["restored_from"],
            "first_post_restore_step": rec2["step"],
            "cold_boot_to_first_step_s": cold_boot_s,
            "warm_boot_to_first_step_s": rec2["boot_to_step_s"],
            "warm_phases": {
                k: rec2[k] for k in
                ("t_devices_s", "t_build_s", "t_restore_s",
                 "t_warmup_s", "t_warmup_wait_s", "t_step_s",
                 "cache_entries_at_boot", "error") if k in rec2
            },
            "loss_after_restore": rec2["loss"],
            "preset": os.environ.get("BENCH_RECOVERY_PRESET", "recovery"),
        },
    }
    return result_line


# -- recovery wedge (CPU mesh): cold restart vs warm restart vs live ----------

LIVE_RESHARD_SPEEDUP_TARGET = 3.0


def _wedge_restart_leg(scratch: str, cache_dir: str, label: str,
                       total_steps: int, save_every: int,
                       timeout: float,
                       restart_cache_dir: str = "") -> dict:
    """One kill-and-restart measurement of the recovery-worker pair,
    with the compile cache rooted at ``cache_dir`` (empty dir = cold
    compile, populated = warm). Runs the workers on a SINGLE CPU device:
    jax 0.4.37 cannot serialize multi-device SPMD executables into the
    persistent cache, so the zero-recompile warm-restart claim is only
    measurable at 1 device — which also biases the ratio AGAINST the
    live leg (a 1-device compile is cheaper than the 8-device SPMD
    one). Returns {"mttr_s", "cache_misses", "restored_from", ...}."""
    import shutil
    import subprocess

    ckpt_dir = os.path.join(scratch, f"ckpt_{label}")
    status_file = os.path.join(scratch, f"status_{label}.jsonl")
    shutil.rmtree(ckpt_dir, ignore_errors=True)
    os.makedirs(ckpt_dir, exist_ok=True)
    os.makedirs(cache_dir, exist_ok=True)
    if os.path.exists(status_file):
        os.remove(status_file)

    env = dict(os.environ)
    env["DLROVER_COMPILE_CACHE_DIR"] = cache_dir
    env["BENCH_IN_RECOVERY_WORKER"] = "1"
    from dlrover_tpu.utils.compile_cache import CPU_ISA_CAP_FLAG

    env["BENCH_PRESET"] = "tiny"
    env["BENCH_PLATFORM"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    # single device (see docstring) + the ISA cap for clean reloads
    env["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=1 " + CPU_ISA_CAP_FLAG
    )
    cmd = [
        sys.executable, os.path.abspath(__file__), "--recovery-worker",
        "--ckpt-dir", ckpt_dir, "--status-file", status_file,
        "--total-steps", str(total_steps), "--save-every", str(save_every),
    ]
    p1 = subprocess.Popen(cmd, env=env)
    last_commit = {"step": -1}

    def _committed_and_progressed(r):
        if r["committed"] >= 0:
            last_commit["step"] = max(last_commit["step"], r["committed"])
        return (
            last_commit["step"] >= save_every
            and r["step"] >= last_commit["step"] + 2
        )

    rec = _wait_status(status_file, _committed_and_progressed, timeout,
                       proc=p1)
    if rec is None:
        p1.kill()
        p1.wait()
        return {"error": f"{label}: phase-1 never committed"}
    p1.kill()  # the injected preemption
    p1.wait()
    t_kill = time.time()
    if restart_cache_dir:
        # a TRULY cold restart: phase 1 populated ``cache_dir`` as it
        # trained, so restarting against it would silently be warm —
        # point the restarted worker at a separate (empty) cache root
        os.makedirs(restart_cache_dir, exist_ok=True)
        env["DLROVER_COMPILE_CACHE_DIR"] = restart_cache_dir
    p2 = subprocess.Popen(cmd, env=env)
    rec2 = _wait_status(
        status_file,
        lambda r: r["t"] > t_kill and r["restored_from"] >= 0,
        timeout, proc=p2,
    )
    p2.kill()
    p2.wait()
    if rec2 is None:
        return {"error": f"{label}: restarted worker never stepped"}
    return {
        "mttr_s": round(rec2["t"] - t_kill, 2),
        "restored_from": rec2["restored_from"],
        "first_step": rec2["step"],
        "cache_misses": rec2.get("cache_misses", -1),
        "cache_hits": rec2.get("cache_hits", -1),
        "cache_entries_at_boot": rec2.get("cache_entries_at_boot", 0),
        "loss": rec2["loss"],
    }


def _wedge_live_leg(trainer, batch, reshard_devices, steps: int = 8,
                    reshard_at: int = 4) -> dict:
    """One in-process live-reshard measurement through the REAL
    executor loop: inject request_live_reshard at dispatch of step
    ``reshard_at`` (the \"failure\" instant), measure wall time to the
    first MATERIALIZED post-reshard optimizer step — the same
    kill-to-first-step semantics as the restart legs."""
    import itertools

    import jax

    from dlrover_tpu.trainer.conf import Configuration
    from dlrover_tpu.trainer.executor import TrainExecutor, TrainHook

    marks = {}

    class ReshardAt(TrainHook):
        def __init__(self, box):
            self.box = box

        def before_step(self, step):
            if step == reshard_at and "t_event" not in marks:
                marks["t_event"] = time.monotonic()
                self.box[0].request_live_reshard(reshard_devices)

        def after_step(self, step, metrics):
            if "t_event" in marks and "t_resumed" not in marks:
                if getattr(self.box[0]._trainer.accelerated.mesh.devices,
                           "size", 0) == marks.get("target_n"):
                    marks["t_resumed"] = time.monotonic()
                    marks["first_step_after"] = step

    marks["target_n"] = (
        len(reshard_devices) if reshard_devices is not None
        else len(jax.devices())
    )
    box = []
    hook = ReshardAt(box)
    executor = TrainExecutor(
        trainer,
        train_iter_fn=lambda: itertools.repeat(batch),
        hooks=[hook],
        conf=Configuration({
            "train_steps": steps, "log_every_steps": 0,
            "train_window": 4, "preemption_grace": False,
        }),
    )
    box.append(executor)
    executor.train_and_evaluate()
    if "t_resumed" not in marks:
        return {"error": "live leg never materialized a post-reshard step"}
    return {
        "mttr_s": round(marks["t_resumed"] - marks["t_event"], 3),
        "first_step_after": marks["first_step_after"],
        "target_devices": marks["target_n"],
    }


def recovery_wedge_result() -> dict:
    """The CPU-mesh recovery wedge: cold process restart vs warm
    (compile-cached) process restart vs in-process live reshard, on the
    same tiny model. Paired runs with alternating order, median of
    per-pair ratios (PR 4 methodology — wall-clock drift on a shared
    1-core box dwarfs the effect otherwise). Also pins post-reshard
    params bit-identical to the drained snapshot, and zero
    persistent-cache misses on the warm same-topology restart leg.

    Env: BENCH_WEDGE_PAIRS (default 3), BENCH_RECOVERY_DIR,
    BENCH_RECOVERY_TIMEOUT (per restart leg, default 240 s).
    """
    import shutil
    import tempfile

    import jax
    import numpy as np
    import optax

    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel.mesh import MeshPlan
    from dlrover_tpu.parallel.strategy import Strategy
    from dlrover_tpu.trainer.elastic import ElasticTrainer

    pairs = int(os.environ.get("BENCH_WEDGE_PAIRS", "3"))
    timeout = float(os.environ.get("BENCH_RECOVERY_TIMEOUT", "240"))
    base = os.environ.get("BENCH_RECOVERY_DIR", "")
    scratch = base or tempfile.mkdtemp(prefix="dlrover_wedge_")
    cold_cache = os.path.join(scratch, "cache_cold")
    warm_cache = os.path.join(scratch, "cache_warm")
    shutil.rmtree(cold_cache, ignore_errors=True)

    devices = jax.devices()
    n_dev = len(devices)
    half = devices[: max(1, n_dev // 2)]

    # the live trainer: same tiny-llama config as the restart workers
    config, batch_rows, seq_len = _pick_config("cpu", "tiny")
    rng = np.random.RandomState(0)
    batch_rows = -(-batch_rows // n_dev) * n_dev
    ids = rng.randint(0, config.vocab_size,
                      size=(batch_rows, seq_len + 1))
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    trainer = ElasticTrainer(
        llama.make_init_fn(config),
        llama.make_loss_fn(config),
        optax.adafactor(1e-3),
        batch,
        strategy=Strategy(mesh=MeshPlan(data=-1), rule_set="llama",
                          remat_policy=""),
    )
    # standby compile: the survivor topology is compiled BEFORE the
    # failure, so the live reshard inside the timed region pays zero
    # recompiles — the production posture (prewarm the N-1 world)
    trainer.prepare()
    trainer.prewarm(devices=half)

    # parity pin: the resharded params are bit-identical to the drained
    # snapshot (outside the timed region; one reshard each way)
    state = trainer.prepare()
    for i in range(3):
        state, _ = trainer.step(state, batch)
    snap_before = jax.device_get(state.params)
    state = trainer.live_reshard(state, devices=half)
    snap_after = jax.device_get(state.params)
    params_identical = all(
        np.asarray(a).tobytes() == np.asarray(b).tobytes()
        for a, b in zip(jax.tree.leaves(snap_before),
                        jax.tree.leaves(snap_after))
    )
    state = trainer.live_reshard(state, devices=None)  # back to full

    cold = _wedge_restart_leg(scratch, cold_cache, "cold",
                              total_steps=60, save_every=5,
                              timeout=timeout,
                              restart_cache_dir=os.path.join(
                                  scratch, "cache_cold_restart"))
    if "error" in cold:
        return {
            "metric": "live_reshard_speedup", "value": 0.0,
            "unit": "x", "vs_baseline": 0.0, "error": cold["error"],
        }
    # prime the warm cache with one UNMEASURED kill+restart cycle: the
    # restore path compiles programs (orbax device_puts, the
    # donation-safety copy) that a never-restarted phase-1 worker has
    # no reason to compile, so the first measured warm leg would
    # otherwise charge those one-time compiles against every later
    # same-topology restart's zero-recompile claim
    prime = _wedge_restart_leg(scratch, warm_cache, "prime",
                               total_steps=60, save_every=5,
                               timeout=timeout)
    if "error" in prime:
        return {
            "metric": "live_reshard_speedup", "value": 0.0,
            "unit": "x", "vs_baseline": 0.0, "error": prime["error"],
        }
    live_runs, warm_runs, ratios = [], [], []
    for i in range(pairs):
        legs = {}

        def run_warm():
            legs["warm"] = _wedge_restart_leg(
                scratch, warm_cache, f"warm{i}", total_steps=60,
                save_every=5, timeout=timeout)

        def run_live():
            # alternate the reshard direction so every leg does real
            # work (a no-op \"reshard\" to the current world would be
            # flattered by the comparison)
            target = half if i % 2 == 0 else None
            legs["live"] = _wedge_live_leg(trainer, batch, target)

        if i % 2 == 0:
            run_warm(); run_live()
        else:
            run_live(); run_warm()
        warm, live = legs["warm"], legs["live"]
        if "error" in warm or "error" in live:
            return {
                "metric": "live_reshard_speedup", "value": 0.0,
                "unit": "x", "vs_baseline": 0.0,
                "error": warm.get("error") or live.get("error"),
            }
        warm_runs.append(warm)
        live_runs.append(live)
        ratios.append(warm["mttr_s"] / max(live["mttr_s"], 1e-6))

    median_ratio = sorted(ratios)[len(ratios) // 2]
    warm_zero_recompiles = all(
        r["cache_misses"] == 0 for r in warm_runs
    )
    result_line = {
        "metric": "live_reshard_speedup",
        "value": round(median_ratio, 2),
        "unit": "x",
        # >= 1 means the >=3x acceptance wedge held
        "vs_baseline": round(median_ratio / LIVE_RESHARD_SPEEDUP_TARGET,
                             3),
        "detail": {
            "live_mttr_s": [r["mttr_s"] for r in live_runs],
            "warm_restart_mttr_s": [r["mttr_s"] for r in warm_runs],
            "cold_restart_mttr_s": cold.get("mttr_s"),
            "cold_error": cold.get("error", ""),
            "prime_restart_mttr_s": prime.get("mttr_s"),
            "pair_ratios": [round(r, 2) for r in ratios],
            "warm_cache_misses": [r["cache_misses"] for r in warm_runs],
            "warm_zero_recompiles": warm_zero_recompiles,
            "params_bit_identical": bool(params_identical),
            "n_devices_live": n_dev,
            "n_devices_restart": 1,
            "restored_from": [r["restored_from"] for r in warm_runs],
        },
    }
    if not params_identical:
        result_line["error"] = ("post-reshard params diverged from the "
                                "drained snapshot")
    elif not warm_zero_recompiles:
        result_line["error"] = ("warm same-topology restart recompiled "
                                "(persistent-cache miss)")
    elif median_ratio < LIVE_RESHARD_SPEEDUP_TARGET:
        result_line["error"] = (
            f"live reshard only {median_ratio:.2f}x faster than a warm "
            f"process restart (target {LIVE_RESHARD_SPEEDUP_TARGET}x)"
        )
    if not base:
        shutil.rmtree(scratch, ignore_errors=True)
    return result_line


def peer_rebuild_result() -> dict:
    """The checkpoint-free recovery leg (ISSUE 15): train -> replicate
    the host snapshot to a surviving peer's DRAM over real RPC -> lose
    the node -> a fresh trainer rebuilds by streaming the regions back
    and ``device_put``-ing against its mesh. Reports the MTTR breakdown
    the peer path is judged on — drain (settle + snapshot), fetch (wire
    stream out of peer DRAM), device_put — plus bytes fetched from
    peers vs storage (pinned 0: no checkpoint directory exists) and the
    bitwise param parity of the rebuilt state.

    Env: BENCH_PEER_REPEATS (default 3; repeats >1 re-run the fetch on
    the already-compiled trainer, isolating transfer cost from the
    one-time compile)."""
    import numpy as np
    import optax

    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.checkpoint import replication as crepl
    from dlrover_tpu.common.config import get_context
    from dlrover_tpu.master.local_master import start_local_master
    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel.mesh import MeshPlan
    from dlrover_tpu.parallel.strategy import Strategy
    from dlrover_tpu.telemetry.events import recent_events
    from dlrover_tpu.trainer.elastic import ElasticTrainer

    import jax

    repeats = int(os.environ.get("BENCH_PEER_REPEATS", "3"))
    ctx = get_context()
    saved = {k: getattr(ctx, k) for k in (
        "snapshot_replicas", "peer_restore",
        "replica_min_interval_secs")}
    ctx.snapshot_replicas = 1
    ctx.peer_restore = True
    ctx.replica_min_interval_secs = 0.0
    master = start_local_master()
    store = crepl.ReplicaStore()
    srv, port = crepl.start_replica_server(store, host="127.0.0.1")
    try:
        holder = MasterClient(master.addr, node_id=9)
        holder.report_replica_endpoint(
            addr=f"127.0.0.1:{port}", budget_mb=256.0,
            snapshot_mb=0.0, step=-1)
        holder.close()

        config, batch_rows, seq_len = _pick_config("cpu", "tiny")
        rng = np.random.RandomState(0)
        n_dev = len(jax.devices())
        batch_rows = -(-batch_rows // n_dev) * n_dev
        ids = rng.randint(0, config.vocab_size,
                          size=(batch_rows, seq_len + 1))
        batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}

        def build(node_client):
            return ElasticTrainer(
                llama.make_init_fn(config),
                llama.make_loss_fn(config),
                optax.adafactor(1e-3), batch,
                strategy=Strategy(mesh=MeshPlan(data=-1),
                                  rule_set="llama", remat_policy=""),
                master_client=node_client,
            )

        client0 = MasterClient(master.addr, node_id=0)
        trainer = build(client0)
        state = trainer.prepare()
        for _ in range(3):
            state, _ = trainer.step(state, batch)
        # drain: settle the in-flight chain, then the one device_get
        t0 = time.monotonic()
        jax.block_until_ready(state)
        snap = trainer.snapshot(state)
        drain_s = time.monotonic() - t0
        replicator = crepl.SnapshotReplicator(client0, node_id=0)
        try:
            t0 = time.monotonic()
            replicator.submit(snap.tree, snap.meta, snap.step)
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline and \
                    not store.inventory().get("0"):
                time.sleep(0.02)
            push_s = time.monotonic() - t0
        finally:
            replicator.stop()
        if not store.inventory().get("0"):
            return {"metric": "peer_rebuild_mttr_s", "value": 0.0,
                    "unit": "s", "vs_baseline": 0.0,
                    "error": "replica never committed on the peer"}
        # the loss: node 0's own store is gone, the master knows
        reporter = MasterClient(master.addr, node_id=0)
        reporter.report_failure(node_rank=0, restart_count=0,
                                error_data="bench kill", level="node")
        reporter.close()

        clientB = MasterClient(master.addr, node_id=0)
        trainerB = build(clientB)
        fetches, puts, wire = [], [], []
        stateB = trainerB.prepare()  # repeat 0: includes the compile
        for _ in range(max(0, repeats - 1)):
            restored = trainerB._try_peer_restore()
            if restored is not None:
                stateB = restored
        done = [r for r in recent_events()
                if r.get("kind") == "peer_rebuild_done"]
        for r in done[-repeats:]:
            fetches.append(float(r["fetch_seconds"]))
            puts.append(float(r["put_seconds"]))
            wire.append(int(r["bytes_from_peers"]))
        if not fetches:
            return {"metric": "peer_rebuild_mttr_s", "value": 0.0,
                    "unit": "s", "vs_baseline": 0.0,
                    "error": "no peer_rebuild_done edge recorded"}
        params_identical = all(
            np.asarray(a).tobytes() == np.asarray(b).tobytes()
            for a, b in zip(jax.tree.leaves(snap.tree),
                            jax.tree.leaves(jax.device_get(stateB)))
        )
        med = sorted(
            f + p for f, p in zip(fetches, puts))[len(fetches) // 2]
        result_line = {
            "metric": "peer_rebuild_mttr_s",
            "value": round(drain_s + med, 3),
            "unit": "s",
            "vs_baseline": round((drain_s + med) / MTTR_TARGET_S, 4),
            "detail": {
                "drain_s": round(drain_s, 3),
                "replicate_push_s": round(push_s, 3),
                "fetch_s": [round(f, 3) for f in fetches],
                "device_put_s": [round(p, 3) for p in puts],
                "bytes_from_peers": wire,
                "bytes_from_storage": 0,
                "snapshot_mb": round(snap.nbytes() / 1e6, 2),
                "params_bit_identical": bool(params_identical),
                "repeats": len(fetches),
                "resumed_step": int(trainerB._host_step),
            },
        }
        if not params_identical:
            result_line["error"] = (
                "peer-rebuilt params diverged from the snapshot")
        client0.close()
        clientB.close()
        return result_line
    finally:
        srv.stop(grace=0)
        master.stop()
        for k, v in saved.items():
            setattr(ctx, k, v)


def _write_wedge_artifacts(result_line: dict):
    """BENCH_r07.json: the wedge line. MTTR_r02.json: the DERIVED MTTR
    report (telemetry.mttr) over this process's event ring — the
    live_reshard incidents the wedge just generated, attributed by the
    same pairing the production timeline uses. GOODPUT_r01.json: the
    derived goodput/badput ledger over the same ring (telemetry.goodput
    — productive / reshard / checkpoint / compile / idle buckets
    partitioning the wedge's wall clock)."""
    here = os.path.dirname(os.path.abspath(__file__))
    artifact = os.environ.get(
        "BENCH_WEDGE_ARTIFACT", os.path.join(here, "BENCH_r07.json"))
    if artifact:
        with open(artifact, "w") as f:
            f.write(json.dumps(result_line) + "\n")
    from dlrover_tpu.telemetry.events import recent_events
    from dlrover_tpu.telemetry.goodput import derive_goodput
    from dlrover_tpu.telemetry.mttr import mttr_report

    report = mttr_report(recent_events(), target_s=MTTR_TARGET_S)
    mttr_path = os.environ.get(
        "BENCH_WEDGE_MTTR", os.path.join(here, "MTTR_r02.json"))
    if mttr_path:
        with open(mttr_path, "w") as f:
            f.write(json.dumps(report) + "\n")
    ledger = derive_goodput(recent_events())
    goodput_path = os.environ.get(
        "BENCH_WEDGE_GOODPUT", os.path.join(here, "GOODPUT_r01.json"))
    if goodput_path:
        with open(goodput_path, "w") as f:
            f.write(json.dumps(ledger) + "\n")


def recovery_main() -> int:
    if os.environ.get("BENCH_PLATFORM", "") == "cpu":
        # the CPU mesh runs the three-way wedge (live vs warm vs cold);
        # real accelerators keep the kill-and-restore MTTR measurement
        # against the BASELINE <90 s target. The live leg reshards a
        # virtual 8-device mesh, so the flag must land before jax
        # initializes in THIS process (the restart legs override it to
        # 1 device in their own subprocess env).
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        _pin_cpu_isa_for_cache()
        # BENCH_RECOVERY_LEG=peer runs ONLY the checkpoint-free
        # peer-rebuild leg (cheap; writes BENCH_r14.json); the default
        # runs the live-vs-restart wedge then the peer leg
        leg = os.environ.get("BENCH_RECOVERY_LEG", "")
        rc = 0
        if leg != "peer":
            result_line = recovery_wedge_result()
            print(json.dumps(result_line))
            if "error" not in result_line:
                _write_wedge_artifacts(result_line)
            rc = 1 if result_line.get("error") else rc
            if leg == "wedge":
                return rc
        peer_line = peer_rebuild_result()
        print(json.dumps(peer_line))
        if "error" not in peer_line:
            here = os.path.dirname(os.path.abspath(__file__))
            artifact = os.environ.get(
                "BENCH_PEER_ARTIFACT",
                os.path.join(here, "BENCH_r14.json"))
            if artifact:
                with open(artifact, "w") as f:
                    f.write(json.dumps(peer_line) + "\n")
        return 1 if peer_line.get("error") else rc
    result_line = recovery_result()
    print(json.dumps(result_line))
    return 1 if result_line.get("error") else 0


def _parse_args(argv):
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--mode",
                   choices=["mfu", "recovery", "dispatch", "replan",
                            "serve"],
                   default="mfu")
    p.add_argument("--recovery-worker", action="store_true",
                   help="internal: run the recovery training worker")
    p.add_argument("--mfu-worker", action="store_true",
                   help="internal: run the MFU measurement worker")
    p.add_argument("--out", default="",
                   help="internal: result path for --mfu-worker")
    p.add_argument("--ckpt-dir", default="")
    p.add_argument("--status-file", default="")
    p.add_argument("--total-steps", type=int, default=60)
    p.add_argument("--save-every", type=int, default=5)
    return p.parse_args(argv)


# -- replan (runtime-optimizer convergence) mode -----------------------------

# wedge target: post-convergence steps/sec with the closed loop vs the
# degraded no-optimizer baseline (same injected straggler either side)
REPLAN_SPEEDUP_TARGET = 1.5


def _replan_leg(slow_s: float, steps: int, poll: bool,
                measure_from: int, measure_to: int) -> dict:
    """One full job against a fresh in-process master (real RPC): two
    fast anchor nodes feed the straggler detector's peer median, then
    the measured node runs with ``slow_s`` of injected host latency per
    DISPATCH (a degraded-but-alive host — the cost a bigger
    ``steps_per_call`` amortizes). ``poll=True`` closes the loop (the
    ``OptimizerPlanHook`` fetches and live-applies the master's plan);
    ``poll=False`` is the degraded baseline. Steps/sec is measured over
    [measure_from, measure_to] materialized steps."""
    import jax
    import jax.numpy as jnp
    import optax

    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.master.local_master import start_local_master
    from dlrover_tpu.parallel.mesh import MeshPlan
    from dlrover_tpu.parallel.strategy import Strategy
    from dlrover_tpu.telemetry.metrics import process_registry
    from dlrover_tpu.trainer.conf import Configuration
    from dlrover_tpu.trainer.elastic import ElasticTrainer
    from dlrover_tpu.trainer.executor import (
        NodeRuntimeReportHook,
        OptimizerPlanHook,
        TrainExecutor,
        TrainHook,
    )

    def make_trainer():
        def init_fn(rng):
            return {"w": jax.random.normal(rng, (4, 2)),
                    "b": jnp.zeros((2,))}

        def loss_fn(params, b, rng):
            pred = b["x"] @ params["w"] + params["b"]
            return jnp.mean((pred - b["y"]) ** 2), {}

        ks = jax.random.split(jax.random.PRNGKey(0), 2)
        x = jax.random.normal(ks[0], (16, 4))
        batch = {"x": x, "y": x @ jax.random.normal(ks[1], (4, 2))}
        trainer = ElasticTrainer(
            init_fn, loss_fn, optax.sgd(0.1), batch,
            strategy=Strategy(mesh=MeshPlan(data=-1)),
        )
        return trainer, batch

    class StepClock(TrainHook):
        def __init__(self):
            self.at = {}

        def after_step(self, step, metrics):
            self.at[step] = time.monotonic()

    class PollEvery(TrainHook):
        def __init__(self, plan_hook, every=6):
            self.plan_hook = plan_hook
            self.every = every

        def after_step(self, step, metrics):
            if step % self.every == 0:
                self.plan_hook.poll_once()

    def run_node(master, node_id, slow=0.0, n_steps=60,
                 with_poll=False):
        # per-node registry reset: the report hook sends CUMULATIVE
        # histogram counts, and every "node" here shares one process
        process_registry().reset()
        client = MasterClient(master.addr, node_id=node_id)
        trainer, batch = make_trainer()
        if slow:
            orig_step, orig_multi = trainer.step, trainer.step_multi

            def step(state, b):
                time.sleep(slow)
                return orig_step(state, b)

            def step_multi(state, group):
                time.sleep(slow)
                return orig_multi(state, group)

            # wrapping the trainer methods (not a hook) makes the
            # injection survive the live retune's program swap: the
            # post-plan speedup is real amortization, not the
            # straggler conveniently vanishing
            trainer.step, trainer.step_multi = step, step_multi
        clock = StepClock()
        ex = TrainExecutor(
            trainer,
            train_iter_fn=lambda: [batch] * n_steps,
            hooks=[NodeRuntimeReportHook(client, every_steps=6,
                                         min_interval_s=0), clock],
            conf=Configuration({
                "train_steps": n_steps, "log_every_steps": 0,
                "train_window": 2, "preemption_grace": False,
                "plan_measure_steps": 16, "plan_poll_secs": 0,
            }),
        )
        ex._master_client = client
        if with_poll:
            plan_hook = OptimizerPlanHook(client, poll_secs=0)
            plan_hook._executor = ex
            ex._hooks.append(PollEvery(plan_hook))
        ex.train_and_evaluate()
        client.close()
        return ex, trainer, clock

    master = start_local_master()
    try:
        run_node(master, 0)
        run_node(master, 1)
        ex, trainer, clock = run_node(
            master, 2, slow=slow_s, n_steps=steps, with_poll=poll)
        dt = clock.at[measure_to] - clock.at[measure_from]
        chosen = [d for d in
                  master.servicer.runtime_optimizer.decisions()
                  if d["outcome"] == "chosen"]
        # the measured node's derived attribution gauges (its registry
        # is still live — run_node resets at ENTRY, not exit)
        from dlrover_tpu.telemetry import names as tmn

        reg = process_registry()
        g_mfu = reg.get(tmn.ATTR_MFU)
        g_frac = reg.get(tmn.ATTR_EXPOSED_COMM_FRAC)
        return {
            "rate": (measure_to - measure_from) / max(dt, 1e-9),
            "finished_steps": int(ex.state.step),
            "steps_per_call": trainer.steps_per_call,
            "chosen": chosen,
            "mfu": (round(g_mfu.value, 12)
                    if g_mfu is not None else None),
            "exposed_comm_frac": (round(g_frac.value, 6)
                                  if g_frac is not None else None),
        }
    finally:
        master.stop()


def replan_result() -> dict:
    """The ISSUE 7 convergence wedge: a 30 ms/dispatch straggler
    mid-run -> straggler verdict -> calibrated re-plan -> live apply
    (no restart, zero recompiles for the prewarmed program) -> the job
    converges to the best surviving config. Paired legs (degraded
    baseline vs closed loop), alternating order, median of per-pair
    post-convergence steps/sec ratios — the PR 4 methodology, since
    wall-clock drift on a shared box dwarfs the effect otherwise.

    Env: BENCH_REPLAN_PAIRS (default 3), BENCH_REPLAN_SLOW_S
    (default 0.03).
    """
    import jax

    from dlrover_tpu.common.config import get_context
    from dlrover_tpu.telemetry.events import recent_events
    from dlrover_tpu.telemetry.names import EventKind

    pairs = int(os.environ.get("BENCH_REPLAN_PAIRS", "3"))
    slow_s = float(os.environ.get("BENCH_REPLAN_SLOW_S", "0.03"))
    ctx = get_context()
    prev_telemetry = ctx.telemetry_enabled
    ctx.telemetry_enabled = True
    try:
        degraded, optimized, ratios = [], [], []
        for i in range(pairs):
            legs = {}

            def run_degraded():
                legs["deg"] = _replan_leg(
                    slow_s, 60, poll=False,
                    measure_from=30, measure_to=60)

            def run_optimized():
                legs["opt"] = _replan_leg(
                    slow_s, 120, poll=True,
                    measure_from=90, measure_to=120)

            if i % 2 == 0:
                run_degraded(); run_optimized()
            else:
                run_optimized(); run_degraded()
            degraded.append(legs["deg"])
            optimized.append(legs["opt"])
            ratios.append(legs["opt"]["rate"]
                          / max(legs["deg"]["rate"], 1e-9))
    finally:
        ctx.telemetry_enabled = prev_telemetry

    median_ratio = sorted(ratios)[len(ratios) // 2]
    plans = [leg["chosen"][0] if leg["chosen"] else None
             for leg in optimized]
    plan_ids = {p["plan_id"] for p in plans if p}
    apply_done = [r for r in recent_events()
                  if r.get("kind") == EventKind.OPTIMIZER_APPLY_DONE
                  and r.get("plan_id") in plan_ids]
    apply_recompiles = sum(r.get("recompiled", 0) for r in apply_done)
    no_restart = all(leg["finished_steps"] == 120 for leg in optimized)
    result_line = {
        "metric": "replan_convergence_speedup",
        "value": round(median_ratio, 2),
        "unit": "x",
        # >= 1 means the closed loop met the 1.5x convergence target
        "vs_baseline": round(median_ratio / REPLAN_SPEEDUP_TARGET, 3),
        "detail": {
            "degraded_steps_per_s": [round(d["rate"], 1)
                                     for d in degraded],
            "optimized_steps_per_s": [round(o["rate"], 1)
                                      for o in optimized],
            "pair_ratios": [round(r, 2) for r in ratios],
            "slow_s_per_dispatch": slow_s,
            "chosen_steps_per_call": [
                p["chosen"]["steps_per_call"] if p else None
                for p in plans],
            "predicted_speedups": [
                p["predicted_speedup"] if p else None for p in plans],
            "realized_speedups": [
                p.get("realized_speedup") if p else None
                for p in plans],
            "apply_recompiles": apply_recompiles,
            "applied_without_restart": no_restart,
            # per-leg attribution: the closed loop's K-amortization
            # shows up as a HIGHER mfu / LOWER exposed-comm fraction
            # on the same injected straggler
            "mfu_per_leg": {
                "degraded": [d.get("mfu") for d in degraded],
                "optimized": [o.get("mfu") for o in optimized],
            },
            "exposed_comm_frac_per_leg": {
                "degraded": [d.get("exposed_comm_frac")
                             for d in degraded],
                "optimized": [o.get("exposed_comm_frac")
                              for o in optimized],
            },
            "n_devices": len(jax.devices()),
        },
    }
    if not all(plans):
        result_line["error"] = (
            "an optimizer leg never chose a plan (no straggler "
            "verdict, or hysteresis rejected every candidate)"
        )
    elif not all(p.get("realized_speedup") for p in plans):
        result_line["error"] = ("an applied plan never reported its "
                                "realized speedup (plan ack missing)")
    elif apply_recompiles:
        result_line["error"] = ("the live apply recompiled — the "
                                "chosen program was not prewarmed")
    elif not no_restart:
        result_line["error"] = "an optimizer leg restarted mid-run"
    elif median_ratio < REPLAN_SPEEDUP_TARGET:
        result_line["error"] = (
            f"post-convergence only {median_ratio:.2f}x the degraded "
            f"baseline (target {REPLAN_SPEEDUP_TARGET}x)"
        )
    return result_line


def replan_main() -> int:
    # the wedge runs on a virtual CPU mesh (the straggler is injected
    # host latency): force the 8-device topology before jax initializes
    if os.environ.get("BENCH_PLATFORM", "") == "cpu":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        _pin_cpu_isa_for_cache()
    result_line = replan_result()
    print(json.dumps(result_line))
    artifact = os.environ.get(
        "BENCH_REPLAN_ARTIFACT",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_r08.json"),
    )
    if artifact and "error" not in result_line:
        with open(artifact, "w") as f:
            f.write(json.dumps(result_line) + "\n")
    return 1 if result_line.get("error") else 0


# -- serve (continuous batching) mode ----------------------------------------

# wedge target: continuous batching vs static batching on the SAME
# mixed-length workload (admission churn is the variable — the static
# tail is what continuous batching removes; on the 1-core CPU mesh the
# per-step cost is flat, so the tokens/sec ratio is the step-count win)
SERVE_SPEEDUP_TARGET = 1.3


def _serve_workload(seed: int = 0, requests: int = 8,
                    prompt_len: int = 6):
    """Mixed-length batch: alternating short/long generations — the
    workload shape where static batching pays its tail."""
    import numpy as np

    rng = np.random.RandomState(seed)
    out = []
    for i in range(requests):
        out.append({
            "prompt": [int(t) for t in
                       rng.randint(0, 256, size=(prompt_len,))],
            "max_new": 2 if i % 2 == 0 else 40,
        })
    return out


def _serve_leg(engine, admission: str, workload,
               resize_to=None, resize_after: int = 0) -> dict:
    """One serving leg on a FRESH pool (the engine and its compiled
    programs are shared across legs — zero recompiles inside every
    timed region, pinned by the caller). Returns tokens/sec + latency
    percentiles + the completion records."""
    from dlrover_tpu.serving.engine import ServeExecutor

    engine.cache = engine.fresh_cache()
    # window=1: slot turnover is the variable under test, and a deeper
    # lag window delays finish detection by its depth in wasted decode
    # steps per short request (the same trade train_window makes —
    # documented in docs/serving.md)
    executor = ServeExecutor(engine, admission=admission,
                             serve_window=1)
    for i, req in enumerate(workload):
        executor.submit(req["prompt"], max_new_tokens=req["max_new"],
                        request_id=f"{admission}-{i}")
    t0 = time.monotonic()
    if resize_to is not None:
        executor.serve(max_steps=resize_after, until_idle=False)
        executor.request_resize(resize_to)
    done = executor.serve()
    wall = time.monotonic() - t0
    tokens = sum(len(r["tokens"]) for r in done)
    ttfts = sorted(r["ttft_s"] for r in done
                   if r["ttft_s"] is not None)
    e2es = sorted(r["e2e_s"] for r in done)
    # the SLO decomposition beside TTFT/e2e: queue-wait (submit ->
    # admit, worker-local mode measures it on the records) and TPOT
    # (decode-phase inter-token: (e2e - ttft) / (tokens - 1))
    waits = sorted(r["queue_wait_s"] for r in done
                   if r.get("queue_wait_s") is not None)
    tpots = sorted(
        (r["e2e_s"] - r["ttft_s"]) / (len(r["tokens"]) - 1)
        for r in done
        if r.get("ttft_s") is not None and len(r["tokens"]) > 1)

    def pct(xs, q):
        return xs[min(len(xs) - 1, int(q * len(xs)))] if xs else None

    return {
        "admission": admission,
        "completed": len(done),
        "tokens": tokens,
        "decode_steps": executor.decode_steps,
        "wall_s": round(wall, 4),
        "tokens_per_s": round(tokens / max(wall, 1e-9), 2),
        "ttft_p50_s": pct(ttfts, 0.50),
        "ttft_p95_s": pct(ttfts, 0.95),
        "e2e_p50_s": pct(e2es, 0.50),
        "e2e_p95_s": pct(e2es, 0.95),
        "queue_wait_p50_s": pct(waits, 0.50),
        "queue_wait_p95_s": pct(waits, 0.95),
        "tpot_p50_s": pct(tpots, 0.50),
        "tpot_p95_s": pct(tpots, 0.95),
        # the slot-seconds partition of this leg's serve loop (sums to
        # slots x serve_wall_s by construction — the per-leg view of
        # `tpurun serve slo --events`)
        "slot_ledger": executor.slot_ledger(),
        "records": done,
    }


# wedge target: prefix pool ON vs OFF on a shared-system-prompt
# workload (the workload shape the pool exists for: every request
# repeats the same leading pages, so ON replaces most prefill chunks
# with page copies; decode is identical, so the tokens/sec ratio is
# the prefill-work win)
PREFIX_SPEEDUP_TARGET = 1.3


def _prefix_workload(seed: int = 1, requests: int = 12,
                     shared_len: int = 32, tail_len: int = 8,
                     max_new: int = 4):
    """Shared-system-prompt batch: one common prefix, distinct tails."""
    import numpy as np

    rng = np.random.RandomState(seed)
    shared = [int(t) for t in rng.randint(0, 256, size=(shared_len,))]
    out = []
    for _ in range(requests):
        tail = [int(t) for t in rng.randint(0, 256, size=(tail_len,))]
        out.append({"prompt": shared + tail, "max_new": max_new})
    return out


def _prefix_leg(engine, workload, tag: str) -> dict:
    """One prefix-wedge leg: fresh slots and a fresh (empty) pool,
    one UNTIMED seeding request that publishes the shared prefix when
    the pool is on (served identically when it is off — the legs run
    the same procedure), then the timed batch."""
    from dlrover_tpu.serving.engine import ServeExecutor

    engine.cache = engine.fresh_cache()
    engine.reset_prefix()
    executor = ServeExecutor(engine, serve_window=1)
    executor.submit(workload[0]["prompt"], max_new_tokens=2,
                    request_id=f"{tag}-seed")
    executor.serve()
    for i, req in enumerate(workload):
        executor.submit(req["prompt"], max_new_tokens=req["max_new"],
                        request_id=f"{tag}-{i}")
    t0 = time.monotonic()
    done = executor.serve()
    wall = time.monotonic() - t0
    recs = [r for r in done if not r["request_id"].endswith("-seed")]
    tokens = sum(len(r["tokens"]) for r in recs)
    return {
        "completed": len(recs),
        "tokens": tokens,
        "wall_s": round(wall, 4),
        "tokens_per_s": round(tokens / max(wall, 1e-9), 2),
        "prefix_hit_tokens": sum(
            int(r.get("prefix_hit_tokens", 0) or 0) for r in recs),
        "records": recs,
    }


def _serve_prefix_replan(engine) -> dict:
    """The replan wedge: an in-process RuntimeOptimizer fed the live
    engine's geometry and the operator's expected-hit-rate prior must
    CHOOSE a nonzero pool under the HBM gate, and the engine must
    apply it through prewarm + retune at zero recompiles — the full
    knob path, master judgment to worker apply."""
    import jax

    from dlrover_tpu.common import comm
    from dlrover_tpu.common.config import get_context
    from dlrover_tpu.master.monitor.node_series import NodeRuntimeStore
    from dlrover_tpu.master.optimizer import RuntimeOptimizer

    spec = engine.program.spec
    ctx = get_context()
    prev_prior = getattr(ctx, "serve_prefix_expected_hit_rate", 0.0)
    ctx.serve_prefix_expected_hit_rate = 0.8
    published = []
    try:
        opt = RuntimeOptimizer(NodeRuntimeStore(),
                               publish=published.append,
                               cooldown_secs=0.0)
        # price at a realistic model scale: the tiny demo model's
        # decode step sits on the host-dispatch FLOOR where every
        # candidate ties (and the churn tie-break rightly keeps every
        # knob unchanged) — the wedge is about the decision PLUMBING,
        # so the optimizer judges a weight-read-bound 7B-class model
        # over the worker's true KV geometry
        opt.update_model_info(comm.ModelInfo(
            num_params=7_000_000_000,
            hidden_size=spec.num_kv_heads * spec.head_dim,
            num_layers=spec.num_layers, seq_len=128))
        opt.update_serving_config(comm.ServeConfigReport(
            node_id=0, world=len(jax.devices()),
            serve_slots=spec.num_slots,
            prefill_chunk=engine.prefill_chunk,
            kv_precision=spec.precision, max_seq=spec.max_seq,
            num_layers=spec.num_layers, kv_heads=spec.num_kv_heads,
            head_dim=spec.head_dim, prefix_pool_pages=0,
            page_size=spec.page_size, prefix_hit_rate=-1.0))
        dec = [d for d in opt.decisions()
               if d["trigger"].startswith("serve:")][-1]
        chosen = dec.get("chosen") or {}
        plan = published[-1] if published else None
        plan_ppp = (getattr(plan, "serve_prefix_pool_pages", -1)
                    if plan is not None else -1)
        out = {
            "outcome": dec.get("outcome"),
            "chosen_key": chosen.get("key"),
            "predicted_speedup": dec.get("predicted_speedup"),
            "plan_prefix_pool_pages": plan_ppp,
            "memory_rejected": len(dec.get("memory_rejected") or []),
        }
        if dec.get("outcome") != "chosen" or plan_ppp <= 0:
            out["error"] = ("optimizer did not choose a nonzero "
                            "prefix pool")
            return out
        # apply on the live engine: prewarm the chosen knob tuple
        # (standby compile, allowed), then retune must be a cache hit
        new_slots = int(chosen.get("serve_slots", spec.num_slots))
        new_chunk = int(chosen.get("prefill_chunk",
                                   engine.prefill_chunk))
        engine.prewarm(serve_slots=new_slots, prefill_chunk=new_chunk,
                       prefix_pool_pages=plan_ppp)
        recompiled = engine.retune(serve_slots=new_slots,
                                   prefill_chunk=new_chunk,
                                   prefix_pool_pages=plan_ppp,
                                   slot_map={})
        out["applied_recompiles"] = int(recompiled)
        # ack: the worker's config echo marks the plan applied and
        # must NOT trigger a chase-our-own-tail replan
        opt.update_serving_config(comm.ServeConfigReport(
            node_id=0, world=len(jax.devices()),
            serve_slots=new_slots, prefill_chunk=new_chunk,
            kv_precision=spec.precision, max_seq=spec.max_seq,
            num_layers=spec.num_layers, kv_heads=spec.num_kv_heads,
            head_dim=spec.head_dim, prefix_pool_pages=plan_ppp,
            page_size=spec.page_size, plan_id=plan.plan_id))
        acked = [d for d in opt.decisions()
                 if d.get("plan_id") == plan.plan_id][-1]
        out["applied"] = bool(acked.get("applied"))
        if recompiled:
            out["error"] = "retune recompiled on a prewarmed knob set"
        elif not out["applied"]:
            out["error"] = "apply ack did not mark the plan applied"
        return out
    finally:
        ctx.serve_prefix_expected_hit_rate = prev_prior


def _serve_prefix_wedge(cfg, params) -> dict:
    """Paired OFF-vs-ON legs (alternating order, median of paired
    ratios) on the shared-system-prompt workload, a bitwise parity
    check between the legs, and the replan wedge — two engines so each
    side keeps its own compiled programs (the OFF engine never even
    builds the copy programs)."""
    from dlrover_tpu.parallel.mesh import MeshPlan
    from dlrover_tpu.parallel.strategy import Strategy
    from dlrover_tpu.serving.engine import ServeEngine

    def build(pool_pages):
        e = ServeEngine(
            cfg, strategy=Strategy(mesh=MeshPlan(data=-1),
                                   rule_set="llama"),
            serve_slots=4, prefill_chunk=8, max_seq=48, page_size=8,
            prefix_pool_pages=pool_pages,
        )
        e.prepare(params)
        return e

    engines = {"off": build(0), "on": build(16)}
    workload = _prefix_workload()
    # warmup: absorb every lazy jit (decode, prefill, and the ON
    # engine's admit/publish copies) outside the timed region
    for mode, eng in engines.items():
        _prefix_leg(eng, _prefix_workload(requests=2),
                    f"warm-{mode}")
    before = {
        mode: (eng.compile_count, eng.program.compiled_cache_size())
        for mode, eng in engines.items()}

    pairs, legs = [], {"off": [], "on": []}
    for i in range(3):
        order = ("off", "on") if i % 2 == 0 else ("on", "off")
        pair = {}
        for mode in order:
            pair[mode] = _prefix_leg(engines[mode], workload,
                                     f"{mode}{i}")
        for mode in ("off", "on"):
            legs[mode].append(pair[mode])
        pairs.append(round(
            pair["on"]["tokens_per_s"]
            / max(pair["off"]["tokens_per_s"], 1e-9), 3))
    ratio = sorted(pairs)[len(pairs) // 2]

    # the parity leg: every completion of the last pair must be
    # BITWISE identical between OFF and ON (copy-on-admit feeds the
    # continuation the same bytes full prefill would have written)
    def by_req(rows):
        return {r["request_id"].split("-", 1)[1]: r["tokens"]
                for r in rows}

    off_toks = by_req(legs["off"][-1]["records"])
    on_toks = by_req(legs["on"][-1]["records"])
    bitwise = (set(off_toks) == set(on_toks) and all(
        off_toks[k] == on_toks[k] for k in off_toks))
    recompiles = {
        mode: (eng.compile_count - before[mode][0],
               eng.program.compiled_cache_size() - before[mode][1])
        for mode, eng in engines.items()}
    zero_recompiles = all(c == 0 and g == 0
                          for c, g in recompiles.values())
    stats = engines["on"].prefix_stats()
    replan = _serve_prefix_replan(engines["off"])

    def strip(rows):
        return [{k: v for k, v in r.items() if k != "records"}
                for r in rows]

    result = {
        "pool_pages": 16,
        "requests_per_leg": len(workload),
        "shared_prefix_tokens": 32,
        "pair_ratios": pairs,
        "tokens_per_s_ratio_median": ratio,
        "target_ratio": PREFIX_SPEEDUP_TARGET,
        "off_legs": strip(legs["off"]),
        "on_legs": strip(legs["on"]),
        "bitwise_parity": bitwise,
        "zero_recompiles_in_timed_legs": zero_recompiles,
        "pool_stats": stats or {},
        "replan": replan,
    }
    if not bitwise:
        result["error"] = "prefix-reused tokens diverged from full " \
                          "prefill"
    elif not zero_recompiles:
        result["error"] = "recompile inside a timed prefix leg"
    elif ratio < PREFIX_SPEEDUP_TARGET:
        result["error"] = (f"on/off ratio {ratio} < "
                           f"{PREFIX_SPEEDUP_TARGET}")
    elif replan.get("error"):
        result["error"] = f"replan: {replan['error']}"
    return result


# wedge target: speculative decode ON vs OFF on a repetitive workload
# (the workload shape self-drafting exists for: templated/structured
# generation where the n-gram proposer finds its continuations in the
# slot's own history; on the CPU dispatch floor the tokens/sec ratio
# is the accepted-tokens-per-step win)
SPEC_SPEEDUP_TARGET = 1.3


# seed tokens whose repeated-token prompt locks the tiny model's
# greedy continuation into a fixed point (probed against the bench's
# deterministic PRNGKey(0) init) — the stand-in for structured /
# templated text, the workload shape prompt-lookup drafting exists for
_SPEC_LOOP_TOKENS = (88, 128, 160)


def _spec_workload(seed: int = 3, requests: int = 8,
                   max_new: int = 32, loops_only: bool = False):
    """Repetitive/structured-text batch: most prompts are repeated
    loop-seed tokens (the n-gram proposer finds the continuation in
    the slot's own history, so drafts land), plus two random prompts
    so the drafting cost on non-repetitive text is priced into the
    same legs. ``loops_only`` drops the random pair — the homogeneous
    shape the planner's per-slot expectation models."""
    import numpy as np

    rng = np.random.RandomState(seed)
    out = []
    for i in range(requests):
        if loops_only or i < requests - 2:
            t = _SPEC_LOOP_TOKENS[i % len(_SPEC_LOOP_TOKENS)]
            prompt = [int(t)] * 12
        else:
            prompt = [int(x) for x in rng.randint(0, 256, size=(12,))]
        out.append({"prompt": prompt, "max_new": max_new})
    return out


def _spec_aggregates(leg: dict) -> dict:
    drafted = sum(int(r.get("spec_drafted_tokens", 0) or 0)
                  for r in leg["records"])
    accepted = sum(int(r.get("spec_accepted_tokens", 0) or 0)
                   for r in leg["records"])
    return {
        "drafted": drafted,
        "accepted": accepted,
        "wasted": drafted - accepted,
        "accept_rate": (round(accepted / drafted, 4)
                        if drafted else -1.0),
    }


def _serve_spec_replan(engine, observed_rate: float) -> dict:
    """The closed loop: an in-process RuntimeOptimizer fed the live
    engine's geometry and the OBSERVED acceptance rate (no prior knob
    exists — spec pricing is evidence-only) must CHOOSE a nonzero K,
    and the engine must apply it through prewarm + retune at zero
    recompiles; then one leg at the applied K checks realized
    tokens-per-step against the planner's E = 1 + rate*K (G106-style
    factor tolerance — the CPU dispatch floor makes E the predicted
    speedup)."""
    import jax

    from dlrover_tpu.common import comm
    from dlrover_tpu.master.monitor.node_series import NodeRuntimeStore
    from dlrover_tpu.master.optimizer import RuntimeOptimizer

    spec = engine.program.spec
    published = []
    opt = RuntimeOptimizer(NodeRuntimeStore(),
                           publish=published.append,
                           cooldown_secs=0.0)
    # price at a realistic model scale (the prefix-replan rationale:
    # the tiny model sits on the dispatch floor where slot/chunk knobs
    # all tie — the wedge is about the spec DECISION plumbing)
    opt.update_model_info(comm.ModelInfo(
        num_params=7_000_000_000,
        hidden_size=spec.num_kv_heads * spec.head_dim,
        num_layers=spec.num_layers, seq_len=128))
    opt.update_serving_config(comm.ServeConfigReport(
        node_id=0, world=len(jax.devices()),
        serve_slots=spec.num_slots,
        prefill_chunk=engine.prefill_chunk,
        kv_precision=spec.precision, max_seq=spec.max_seq,
        num_layers=spec.num_layers, kv_heads=spec.num_kv_heads,
        head_dim=spec.head_dim, page_size=spec.page_size,
        spec_draft_len=0, spec_accept_rate=float(observed_rate)))
    dec = [d for d in opt.decisions()
           if d["trigger"].startswith("serve:")][-1]
    chosen = dec.get("chosen") or {}
    plan = published[-1] if published else None
    plan_k = (getattr(plan, "serve_spec_draft_len", -1)
              if plan is not None else -1)
    out = {
        "observed_accept_rate": round(float(observed_rate), 4),
        "outcome": dec.get("outcome"),
        "chosen_key": chosen.get("key"),
        "predicted_speedup": dec.get("predicted_speedup"),
        "plan_spec_draft_len": plan_k,
    }
    if dec.get("outcome") != "chosen" or plan_k <= 0:
        out["error"] = ("optimizer did not choose a nonzero draft "
                        "length from the observed acceptance rate")
        return out
    # apply on the live engine: standby-compile the chosen knob tuple,
    # then the live swap must be a program-cache hit
    new_slots = int(chosen.get("serve_slots", spec.num_slots))
    new_chunk = int(chosen.get("prefill_chunk", engine.prefill_chunk))
    engine.prewarm(serve_slots=new_slots, prefill_chunk=new_chunk,
                   spec_draft_len=plan_k)
    recompiled = engine.retune(serve_slots=new_slots,
                               prefill_chunk=new_chunk,
                               spec_draft_len=plan_k, slot_map={})
    out["applied_recompiles"] = int(recompiled)
    out["applied_spec_draft_len"] = int(engine.program.spec_k)
    # ack: the worker's config echo marks the plan applied and must
    # not trigger a chase-our-own-tail replan
    opt.update_serving_config(comm.ServeConfigReport(
        node_id=0, world=len(jax.devices()),
        serve_slots=new_slots, prefill_chunk=new_chunk,
        kv_precision=spec.precision, max_seq=spec.max_seq,
        num_layers=spec.num_layers, kv_heads=spec.num_kv_heads,
        head_dim=spec.head_dim, page_size=spec.page_size,
        spec_draft_len=plan_k, spec_accept_rate=float(observed_rate),
        plan_id=plan.plan_id))
    acked = [d for d in opt.decisions()
             if d.get("plan_id") == plan.plan_id][-1]
    out["applied"] = bool(acked.get("applied"))
    if recompiled:
        out["error"] = "retune recompiled on a prewarmed knob set"
    elif not out["applied"]:
        out["error"] = "apply ack did not mark the plan applied"
    if out.get("error"):
        return out
    # the applied-K leg: realized PER-SLOT tokens-per-step vs the
    # planner's E = 1 + rate*K. Homogeneous loop prompts only: the
    # planner's expectation is per-slot, so a leg where two straggler
    # slots run while the rest sit idle would under-count the active
    # denominator — the homogeneous shape keeps every slot active
    # until the batch finishes together
    workload = _spec_workload(seed=5, loops_only=True)
    leg = _serve_leg(engine, "continuous", workload)
    applied = _spec_aggregates(leg)
    active = min(len(workload), engine.program.spec.num_slots)
    realized = (leg["tokens"] / max(leg["decode_steps"], 1)
                / max(active, 1))
    # price the expectation from the APPLIED leg's own acceptance at
    # the applied K (the observed_rate fed the decision; the audit
    # checks the pricing FORMULA against what that K then realized)
    rate = max(0.0, applied["accept_rate"])
    expected = 1.0 + rate * plan_k
    out["applied_leg"] = {
        "tokens": leg["tokens"],
        "decode_steps": leg["decode_steps"],
        "active_slots": active,
        "tokens_per_step_per_slot": round(realized, 3),
        "spec": applied,
    }
    out["expected_tokens_per_step"] = round(expected, 3)
    out["tokens_per_step_frac"] = round(realized / expected, 3)
    # G106-style factor tolerance: prefill ticks and the final ragged
    # steps dilute the mean — the gate is order-of-magnitude honesty,
    # not a point match
    if not (expected / 3.0 <= realized <= expected * 3.0):
        out["error"] = (
            f"realized {realized:.2f} tokens/step/slot outside 3x of "
            f"the predicted {expected:.2f}")
    return out


def _serve_spec_wedge(cfg, params) -> dict:
    """Paired spec-OFF-vs-ON legs (alternating order, median of paired
    ratios) on the repetitive workload, a bitwise parity check between
    the legs, the zero-recompile pin, and the closed replan loop — two
    engines so each side keeps its own compiled programs (the OFF
    engine never builds a verify program until the replan leg turns
    it on)."""
    from dlrover_tpu.parallel.mesh import MeshPlan
    from dlrover_tpu.parallel.strategy import Strategy
    from dlrover_tpu.serving.engine import ServeEngine

    def build(draft_len):
        e = ServeEngine(
            cfg, strategy=Strategy(mesh=MeshPlan(data=-1),
                                   rule_set="llama"),
            serve_slots=4, prefill_chunk=8, max_seq=48, page_size=8,
            spec_draft_len=draft_len,
        )
        e.prepare(params)
        return e

    engines = {"off": build(0), "on": build(4)}
    # the timed ratio legs run the repetitive-text workload (the one
    # the ≥1.3x gate is defined on); the mixed workload — loop prompts
    # plus adversarial random prompts that draft ~nothing — runs as an
    # extra untimed parity leg below
    workload = _spec_workload(loops_only=True)
    # warmup: absorb every lazy jit (decode, prefill, and the ON
    # engine's verify) outside the timed region
    for mode, eng in engines.items():
        _serve_leg(eng, "continuous", _spec_workload(requests=2))
    before = {
        mode: (eng.compile_count, eng.program.compiled_cache_size())
        for mode, eng in engines.items()}

    pairs, step_pairs, legs = [], [], {"off": [], "on": []}
    for i in range(3):
        order = ("off", "on") if i % 2 == 0 else ("on", "off")
        pair = {}
        for mode in order:
            pair[mode] = _serve_leg(engines[mode], "continuous",
                                    workload)
        for mode in ("off", "on"):
            legs[mode].append(pair[mode])
        pairs.append(round(
            pair["on"]["tokens_per_s"]
            / max(pair["off"]["tokens_per_s"], 1e-9), 3))
        step_pairs.append(round(
            pair["off"]["decode_steps"]
            / max(pair["on"]["decode_steps"], 1), 3))
    ratio = sorted(pairs)[len(pairs) // 2]
    step_ratio = sorted(step_pairs)[len(step_pairs) // 2]

    # the parity leg: every completion of the last pair must be
    # BITWISE identical between OFF and ON (the acceptance contract:
    # spec emits exactly the plain-greedy stream)
    def by_req(rows):
        return {r["request_id"]: r["tokens"] for r in rows}

    off_toks = by_req(legs["off"][-1]["records"])
    on_toks = by_req(legs["on"][-1]["records"])
    bitwise = (set(off_toks) == set(on_toks) and all(
        off_toks[k] == on_toks[k] for k in off_toks))
    # second parity leg on the MIXED workload: random prompts whose
    # drafts mostly miss must still emit the exact greedy stream
    mixed = _spec_workload()
    mixed_pair = {mode: _serve_leg(engines[mode], "continuous", mixed)
                  for mode in ("off", "on")}
    moff, mon = (by_req(mixed_pair["off"]["records"]),
                 by_req(mixed_pair["on"]["records"]))
    bitwise = bitwise and (set(moff) == set(mon) and all(
        moff[k] == mon[k] for k in moff))
    recompiles = {
        mode: (eng.compile_count - before[mode][0],
               eng.program.compiled_cache_size() - before[mode][1])
        for mode, eng in engines.items()}
    zero_recompiles = all(c == 0 and g == 0
                          for c, g in recompiles.values())
    spec_stats = _spec_aggregates(legs["on"][-1])
    replan = _serve_spec_replan(engines["off"],
                                spec_stats["accept_rate"])

    def strip(rows):
        return [{**{k: v for k, v in r.items() if k != "records"},
                 "spec": _spec_aggregates(r)} for r in rows]

    result = {
        "draft_len": 4,
        "requests_per_leg": len(workload),
        "pair_ratios": pairs,
        "step_ratios": step_pairs,
        "tokens_per_s_ratio_median": ratio,
        "decode_steps_ratio_median": step_ratio,
        "target_ratio": SPEC_SPEEDUP_TARGET,
        "off_legs": strip(legs["off"]),
        "on_legs": strip(legs["on"]),
        "accept_rate": spec_stats["accept_rate"],
        "mixed_leg_spec": _spec_aggregates(mixed_pair["on"]),
        "bitwise_parity": bitwise,
        "zero_recompiles_in_timed_legs": zero_recompiles,
        "replan": replan,
    }
    if not bitwise:
        result["error"] = ("speculated tokens diverged from plain "
                           "greedy decode")
    elif not zero_recompiles:
        result["error"] = "recompile inside a timed spec leg"
    elif ratio < SPEC_SPEEDUP_TARGET:
        result["error"] = (f"on/off ratio {ratio} < "
                           f"{SPEC_SPEEDUP_TARGET}")
    elif replan.get("error"):
        result["error"] = f"replan: {replan['error']}"
    return result


def serve_result() -> dict:
    """The continuous-batching wedge: paired static-vs-continuous legs
    (alternating order, median of paired ratios — the established
    methodology), plus one live 8->4 resize leg that must complete
    every request (dropped == 0) with zero recompiles on the prewarmed
    survivor topology."""
    import jax

    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel.mesh import MeshPlan
    from dlrover_tpu.parallel.strategy import Strategy
    from dlrover_tpu.serving.engine import ServeEngine

    t_start = time.time()
    if len(jax.devices()) < 2:
        # a 1-device world would run a VACUOUS 1->1 "resize" and
        # record it as a passing wedge — refuse loudly instead
        return {
            "metric": "llama_serve_continuous_batching",
            "error": "resize leg needs >= 2 devices; run with "
                     "BENCH_PLATFORM=cpu for the virtual 8-device "
                     "mesh",
        }
    cfg = llama.llama_tiny()
    params = llama.init(jax.random.PRNGKey(0), cfg)
    engine = ServeEngine(
        cfg, strategy=Strategy(mesh=MeshPlan(data=-1),
                               rule_set="llama"),
        serve_slots=4, prefill_chunk=8, max_seq=48, page_size=8,
    )
    engine.prepare(params)
    workload = _serve_workload(requests=16)
    # warmup: compile decode+prefill once, outside every timed region
    # (both admission modes, so neither first timed leg pays a stray
    # one-off jit)
    _serve_leg(engine, "continuous", _serve_workload(requests=2))
    _serve_leg(engine, "static", _serve_workload(requests=2))
    compiles_before = engine.compile_count
    cache_before = engine.program.compiled_cache_size()

    pairs = []
    legs = {"static": [], "continuous": []}
    for i in range(3):
        order = (("static", "continuous") if i % 2 == 0
                 else ("continuous", "static"))
        pair = {}
        for admission in order:
            pair[admission] = _serve_leg(engine, admission, workload)
        legs["static"].append(pair["static"])
        legs["continuous"].append(pair["continuous"])
        pairs.append(round(
            pair["continuous"]["tokens_per_s"]
            / max(pair["static"]["tokens_per_s"], 1e-9), 3))
    ratio = sorted(pairs)[len(pairs) // 2]

    # the resize leg: prewarm the survivor world, then resize live
    # mid-stream under in-flight traffic — zero dropped requests
    survivors = jax.devices()[: max(1, len(jax.devices()) // 2)]
    pre_prewarm = engine.compile_count
    engine.prewarm(devices=survivors)
    prewarm_compiles = engine.compile_count - pre_prewarm
    resize_compiles_before = engine.compile_count
    resize_leg = _serve_leg(engine, "continuous", workload,
                            resize_to=survivors, resize_after=4)
    resize_recompiled = engine.compile_count - resize_compiles_before
    # restore the full world for any later consumer of the engine
    engine.live_resize(devices=None)

    # only the prewarm's standby compile is allowed after warmup
    recompiles = (engine.compile_count - compiles_before
                  - prewarm_compiles)
    steady_cache_growth = (
        engine.program.compiled_cache_size() - cache_before)

    def strip(rows):
        return [{k: v for k, v in r.items() if k != "records"}
                for r in rows]

    result = {
        "metric": "llama_serve_continuous_batching",
        "model": "llama_tiny",
        "platform": "cpu",
        "slots": engine.serve_slots,
        "prefill_chunk": engine.prefill_chunk,
        "requests_per_leg": len(workload),
        "pair_ratios": pairs,
        "tokens_per_s_ratio_median": ratio,
        "target_ratio": SERVE_SPEEDUP_TARGET,
        "static_legs": strip(legs["static"]),
        "continuous_legs": strip(legs["continuous"]),
        "resize": {
            "world_from": len(jax.devices()),
            "world_to": len(survivors),
            "completed": resize_leg["completed"],
            "submitted": len(workload),
            "dropped": len(workload) - resize_leg["completed"],
            "recompiled": resize_recompiled,
            "tokens_per_s": resize_leg["tokens_per_s"],
        },
        "zero_recompiles_in_timed_legs": recompiles == 0
        and steady_cache_growth == 0,
        "note": (
            "CPU numbers recorded, not gated (1-core box; the ratio "
            "is the admission-churn step-count win, which transfers); "
            "hardware row pending the TPU tunnel"
        ),
        "elapsed_s": round(time.time() - t_start, 1),
    }
    # the prefix-cache and speculative-decode wedges ride the same
    # artifact (fresh engines — the continuous-batching numbers above
    # are already closed)
    result["prefix"] = _serve_prefix_wedge(cfg, params)
    result["spec"] = _serve_spec_wedge(cfg, params)
    result["elapsed_s"] = round(time.time() - t_start, 1)
    if result["resize"]["dropped"]:
        result["error"] = (
            f"resize dropped {result['resize']['dropped']} requests")
    elif result["resize"]["recompiled"]:
        result["error"] = "resize recompiled on a prewarmed topology"
    elif not result["zero_recompiles_in_timed_legs"]:
        result["error"] = "recompile inside a timed serving leg"
    elif ratio < SERVE_SPEEDUP_TARGET:
        result["error"] = (
            f"continuous/static ratio {ratio} < "
            f"{SERVE_SPEEDUP_TARGET}")
    elif result["prefix"].get("error"):
        result["error"] = f"prefix: {result['prefix']['error']}"
    elif result["spec"].get("error"):
        result["error"] = f"spec: {result['spec']['error']}"
    return result


def serve_main() -> int:
    # the wedge runs on a virtual CPU mesh (the resize leg needs a
    # world to shrink): force the 8-device topology before jax
    # initializes, the replan_main pattern
    if os.environ.get("BENCH_PLATFORM", "") == "cpu":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        _pin_cpu_isa_for_cache()
    result_line = serve_result()
    print(json.dumps(result_line))
    artifact = os.environ.get(
        "BENCH_SERVE_ARTIFACT",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_r16.json"),
    )
    if artifact:
        with open(artifact, "w") as f:
            f.write(json.dumps(result_line) + "\n")
    return 1 if result_line.get("error") else 0


if __name__ == "__main__":
    args = _parse_args(sys.argv[1:])
    if args.recovery_worker:
        sys.exit(_recovery_worker(args.ckpt_dir, args.status_file,
                                  args.total_steps, args.save_every))
    if args.mfu_worker:
        sys.exit(_mfu_worker(args.out))
    if args.mode == "recovery":
        sys.exit(recovery_main())
    if args.mode == "dispatch":
        sys.exit(dispatch_main())
    if args.mode == "replan":
        sys.exit(replan_main())
    if args.mode == "serve":
        sys.exit(serve_main())
    sys.exit(main())
