"""Headline benchmark: Llama-family pretraining MFU on the local accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is measured MFU / 0.45 (the BASELINE.json Llama-2-7B MFU
target for v5p-32, applied per-chip here since the harness exposes one
chip; multi-chip scaling is validated separately via __graft_entry__.
dryrun_multichip).

Env knobs:
  BENCH_PLATFORM=cpu     run the benchmark logic on CPU (smoke test)
  BENCH_STEPS=N          timed steps (default 10)
  BENCH_PRESET=tiny|1b|long  model size; "long" = 16k-token context on
                         one chip (full remat + chunked lm head)
  BENCH_SEQ=N            sequence length override
  BENCH_BATCH=N          batch rows for the TPU preset (default 4)
  BENCH_REMAT=policy     per-layer remat policy (default dots_saveable)
  BENCH_FLASH=0|1        Pallas flash kernel on/off (default 1)
  BENCH_HEAD_CHUNK=N     fused chunked lm-head loss chunk size (0=off)
"""

from __future__ import annotations

import json
import os
import sys
import time

MFU_TARGET = 0.45

# peak bf16 FLOP/s per chip by device kind (public spec sheets)
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5": 459e12,  # v5p
    "TPU v5p": 459e12,
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v6 lite": 918e12,  # v6e/trillium
    "TPU v6e": 918e12,
    "cpu": 5e11,  # nominal, for smoke runs only
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu")
    # longest prefix wins: "TPU v5 lite" must match its own entry, not
    # the "TPU v5" (v5p) one
    best = ""
    for name in PEAK_FLOPS:
        if kind.lower().startswith(name.lower()) and len(name) > len(best):
            best = name
    if best:
        return PEAK_FLOPS[best]
    return PEAK_FLOPS.get("cpu", 5e11)


def _pick_config(platform: str, preset: str):
    from dlrover_tpu.models import llama
    import jax.numpy as jnp

    if preset == "tiny" or platform == "cpu":
        cfg = llama.llama_tiny(
            num_layers=2, max_seq_len=128,
            use_flash=False,
        )
        return cfg, 4, 128
    # ~1.3B-param llama sized for a single 16GB chip with bf16 params
    seq = int(os.environ.get("BENCH_SEQ", "0"))
    if preset == "long":
        # long-context single-chip: flash attention + full remat +
        # chunked lm head keep memory linear in sequence length
        seq = seq or 16384
        batch = int(os.environ.get("BENCH_BATCH", "1"))
        remat = os.environ.get("BENCH_REMAT", "full")
        os.environ.setdefault("BENCH_HEAD_CHUNK", "1024")
    else:
        seq = seq or 2048
        batch = int(os.environ.get("BENCH_BATCH", "4"))
        remat = os.environ.get("BENCH_REMAT", "dots_saveable")
    cfg = llama.llama2_7b(
        hidden_size=2048,
        intermediate_size=5504,
        num_layers=16,
        num_heads=16,
        num_kv_heads=16,
        max_seq_len=seq,
        param_dtype=jnp.bfloat16,
        compute_dtype=jnp.bfloat16,
        remat_policy=remat,
        use_flash=os.environ.get("BENCH_FLASH", "1") == "1",
    )
    return cfg, batch, seq


def main() -> int:
    platform_override = os.environ.get("BENCH_PLATFORM", "")
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    preset = os.environ.get("BENCH_PRESET", "")

    import jax

    if platform_override:
        jax.config.update("jax_platforms", platform_override)
    try:
        devices = jax.devices()
    except Exception as e:
        print(json.dumps({
            "metric": "llama_pretrain_mfu", "value": 0.0, "unit": "mfu",
            "vs_baseline": 0.0, "error": f"no devices: {e}"[:200],
        }))
        return 1

    import jax.numpy as jnp
    import optax

    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel.accelerate import accelerate
    from dlrover_tpu.parallel.mesh import MeshPlan
    from dlrover_tpu.parallel.strategy import Strategy

    platform = devices[0].platform
    config, batch_size, seq_len = _pick_config(
        platform_override or platform, preset
    )

    import numpy as np

    rng = np.random.RandomState(0)
    ids = rng.randint(0, config.vocab_size, size=(batch_size, seq_len + 1))
    batch = {
        "input_ids": jnp.asarray(ids[:, :-1]),
        "labels": jnp.asarray(ids[:, 1:]),
    }

    n_dev = len(devices)
    head_chunk = int(os.environ.get("BENCH_HEAD_CHUNK", "0"))
    result = accelerate(
        llama.make_init_fn(config),
        llama.make_loss_fn(config, head_chunk=head_chunk),
        optax.adafactor(1e-3),
        batch,
        strategy=Strategy(
            mesh=MeshPlan(data=1, fsdp=n_dev),
            rule_set="llama",
            # the model already applies per-layer remat (config.remat_policy
            # inside the scan); wrapping the loss again would double-remat
            remat_policy="",
        ),
        devices=devices,
    )
    state = result.init_fn(jax.random.PRNGKey(0))
    sharded = result.shard_batch(batch)

    t0 = time.time()
    state, metrics = result.train_step(state, sharded, jax.random.PRNGKey(0))
    # device_get of a value that depends on the whole step is the only
    # reliable sync point: on tunneled platforms block_until_ready can
    # return before the remote executable has finished
    jax.device_get(metrics["loss"])
    jax.block_until_ready(state)
    compile_and_first_step = time.time() - t0

    t0 = time.time()
    for i in range(steps):
        state, metrics = result.train_step(
            state, sharded, jax.random.PRNGKey(i + 1)
        )
    # the state dependency chain makes the last step's loss transitively
    # depend on every timed step
    jax.device_get(metrics["loss"])
    jax.block_until_ready(state)
    step_time = (time.time() - t0) / steps

    tokens_per_step = batch_size * seq_len
    # 6N forward+backward FLOPs per token + causal attention term
    n_params = llama.param_count(config)
    attn_flops_tok = (
        12 * config.num_layers * config.hidden_size * seq_len * 0.5
    )
    flops_per_step = (6.0 * n_params + attn_flops_tok) * tokens_per_step
    achieved = flops_per_step / step_time
    peak = _peak_flops(devices[0]) * n_dev
    mfu = achieved / peak

    result_line = {
        "metric": "llama_pretrain_mfu",
        "value": round(mfu, 4),
        "unit": "mfu",
        "vs_baseline": round(mfu / MFU_TARGET, 4),
        "detail": {
            "device_kind": devices[0].device_kind,
            "n_devices": n_dev,
            "params": n_params,
            "tokens_per_s": round(tokens_per_step / step_time, 1),
            "step_time_s": round(step_time, 4),
            "compile_plus_first_step_s": round(compile_and_first_step, 1),
            "final_loss": float(jax.device_get(metrics["loss"])),
        },
    }
    print(json.dumps(result_line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
