"""Knob sweep runner over ``bench.py`` (wedge-proof by construction).

Each point is one ``python bench.py`` invocation — which since round 4
runs its measurement in a killable subprocess with a hard timeout and
emits exactly one JSON line — so an abandoned compile can no longer
wedge the whole sweep session (the round-3 incident,
``docs/bench_tuning.md``).

Usage:
  python benchmarks/sweep.py --preset long \
      --grid BENCH_BLOCK_Q=512,1024 BENCH_HEAD_CHUNK=256,512 \
      --timeout 900

Prints one result line per point and a sorted summary; writes
``sweep_results.jsonl`` next to this file (append-only, so a killed
sweep keeps its finished points).
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def parse_grid(specs):
    grid = {}
    for spec in specs:
        key, _, values = spec.partition("=")
        if not values:
            raise SystemExit(f"bad --grid entry {spec!r} (KEY=v1,v2)")
        grid[key] = values.split(",")
    return grid


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="")
    p.add_argument("--grid", nargs="*", default=[])
    p.add_argument("--timeout", type=float, default=1200.0,
                   help="per-point bench timeout (BENCH_MFU_TIMEOUT)")
    p.add_argument("--steps", default="")
    p.add_argument("--out", default=os.path.join(HERE,
                                                 "sweep_results.jsonl"))
    args = p.parse_args()

    grid = parse_grid(args.grid)
    keys = sorted(grid)
    points = list(itertools.product(*(grid[k] for k in keys))) or [()]
    results = []
    for values in points:
        knobs = dict(zip(keys, values))
        env = dict(os.environ)
        env.update(knobs)
        env["BENCH_SKIP_RECOVERY"] = "1"
        env["BENCH_MFU_TIMEOUT"] = str(args.timeout)
        if args.preset:
            env["BENCH_PRESET"] = args.preset
        if args.steps:
            env["BENCH_STEPS"] = args.steps
        label = " ".join(f"{k}={v}" for k, v in knobs.items()) or "default"
        t0 = time.time()
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(REPO, "bench.py")],
                env=env, capture_output=True, text=True,
                timeout=args.timeout + 420,  # probe+retry headroom
            )
            lines = [ln for ln in proc.stdout.splitlines()
                     if ln.startswith("{")]
            rec = json.loads(lines[-1]) if lines else {
                "error": f"no JSON (rc={proc.returncode})"
            }
        except subprocess.TimeoutExpired:
            rec = {"error": "sweep-level timeout"}
        rec["_knobs"] = knobs
        rec["_wall_s"] = round(time.time() - t0, 1)
        results.append(rec)
        with open(args.out, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(f"{label:50s} -> "
              f"{rec.get('value', 0.0)} {rec.get('unit', '')} "
              f"({rec.get('error', 'ok')}) [{rec['_wall_s']}s]",
              flush=True)

    good = [r for r in results if not r.get("error")]
    good.sort(key=lambda r: -r.get("value", 0.0))
    print("\n== best first ==")
    for r in good:
        knobs = " ".join(f"{k}={v}" for k, v in r["_knobs"].items())
        print(f"{r['value']:8.4f}  {knobs}")
    return 0 if good else 1


if __name__ == "__main__":
    sys.exit(main())
