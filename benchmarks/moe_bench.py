"""MoE dispatch cost: gather vs einsum dispatch vs iso-FLOPs dense FFN.

Substantiates the fast-dispatch claim (VERDICT r4 missing #2): the
reference delegates its MoE hot path to a custom CUDA backend because
one-hot dispatch dominates expert FLOPs
(``atorch/atorch/modules/moe/moe_layer.py:511`` fastmoe; all-to-all at
``:87``). On TPU the equivalent win comes from slot-gather dispatch
(``ops/moe._moe_compute_gather``): data movement O(T*D) instead of the
[T,E,C] einsums' capacity_factor*T^2*D FLOPs.

Measures fwd+bwd step time of
  - the MoE layer with dispatch="gather" (the default),
  - the MoE layer with dispatch="einsum" (the reference check),
  - a dense FFN with the same per-token FLOPs as the experts' matmuls
    (top_k * d_ff wide) — the iso-FLOPs floor,
and reports dispatch overhead = (moe - dense) / dense.

Row provenance (which rows mean what, where):
  - dense / gather / einsum: timed on any platform; CPU uses reduced
    shapes (per-op overheads inflate ratios there — labeled).
  - grouped (dropless, per-shard): HARDWARE-ONLY — on CPU the Pallas
    kernel runs under the interpreter, so a CPU time would measure the
    interpreter, not the kernel. The row is omitted off-TPU.
  - grouped_ep (dropless, expert-parallel all-to-all): timed on TPU;
    on a multi-device CPU mesh (XLA_FLAGS=
    --xla_force_host_platform_device_count=8) the row RUNS in
    interpret mode and is emitted with "interpret": true — it proves
    the shard_map + all_to_all wiring end to end (correctness/recompile
    behavior), but its milliseconds measure the interpreter and must
    not be compared against the hardware rows.

Run: ``python benchmarks/moe_bench.py`` (TPU host or CPU).
Prints one JSON line per config.
"""

from __future__ import annotations

import json
import os
import sys
import time

# repo-root import without PYTHONPATH (which breaks the tunneled TPU
# plugin's sitecustomize registration on this harness)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.ops.moe import MoEConfig, init_moe_params, moe_ffn

# (batch, seq, d_model, d_ff, num_experts, top_k)
CONFIGS = [
    (8, 1024, 1024, 2816, 8, 1),
    (8, 1024, 1024, 2816, 8, 2),
    (4, 2048, 2048, 5632, 8, 2),
]
# CPU can't push the TPU shapes through the einsum path in bounded time
# (the [T,E,C] einsums are ~170 GFLOPs per call at T=8k — that cost IS
# the finding); scaled-down shapes show the same overhead ratios
CONFIGS_CPU = [
    (2, 256, 256, 704, 8, 1),
    (2, 256, 256, 704, 8, 2),
    (1, 512, 512, 1408, 8, 2),
]
STEPS = 10


def _time_step(fn, *args):
    step = jax.jit(fn)
    jax.device_get(step(*args))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(STEPS):
        out = step(*args)
    # device_get of a dependent scalar: the only reliable sync on the
    # tunneled platform (see flash_bench.py)
    jax.device_get(out)
    return (time.perf_counter() - t0) / STEPS


def _ep_mesh():
    """An expert submesh over every local device (None when the host
    has a single device or the expert count wouldn't divide it)."""
    n = jax.device_count()
    if n < 2:
        return None
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()).reshape(n), ("expert",))


def bench_config(b, s, d, f, e, k, dtype=jnp.bfloat16):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(b, s, d), dtype)
    params = jax.tree.map(
        lambda a: a.astype(dtype),
        init_moe_params(jax.random.PRNGKey(0), d, f, e),
    )

    def moe_loss(dispatch, **cfg_kw):
        cfg = MoEConfig(num_experts=e, top_k=k, dispatch=dispatch,
                        **cfg_kw)

        def loss(p, x):
            o, aux, _ = moe_ffn(p, x, cfg, activation=jax.nn.silu)
            return jnp.sum(o.astype(jnp.float32) ** 2) + aux

        def step(p, x):
            l, g = jax.value_and_grad(loss)(p, x)
            return l + sum(
                jnp.sum(jnp.abs(a).astype(jnp.float32))
                for a in jax.tree.leaves(g)
            )

        return step

    # iso-FLOPs dense floor: each routed token does 2 matmuls of width
    # d_ff per chosen expert -> top_k * d_ff wide dense FFN
    wf = f * k
    dense_p = {
        "up": jnp.asarray(rng.randn(d, wf) / np.sqrt(d), dtype),
        "down": jnp.asarray(rng.randn(wf, d) / np.sqrt(wf), dtype),
    }

    def dense_step(p, x):
        def loss(p, x):
            h = jax.nn.silu(x @ p["up"])
            return jnp.sum((h @ p["down"]).astype(jnp.float32) ** 2)

        l, g = jax.value_and_grad(loss)(p, x)
        return l + sum(
            jnp.sum(jnp.abs(a).astype(jnp.float32))
            for a in jax.tree.leaves(g)
        )

    on_cpu = jax.devices()[0].platform == "cpu"
    t_dense = _time_step(dense_step, dense_p, x)
    t_gather = _time_step(moe_loss("gather"), params, x)
    t_einsum = _time_step(moe_loss("einsum"), params, x)
    # the per-shard DROPLESS grouped kernel only times meaningfully on
    # real hardware — the CPU run would measure the Pallas interpreter,
    # not the kernel (correctness on CPU is tests/test_ops.py's job).
    # HARDWARE-ONLY row.
    t_grouped = (None if on_cpu
                 else _time_step(moe_loss("grouped"), params, x))
    # the EXPERT-PARALLEL dropless path (shard_map + all_to_all around
    # the kernel): real timing on TPU; on a multi-device CPU mesh it
    # runs in interpret mode — wiring proof, interpreter milliseconds
    t_ep, ep_interpret, ep_degree = None, on_cpu, 0
    t_ep_chunked, chunks = None, 4
    mesh = _ep_mesh()
    if mesh is not None and e % mesh.devices.size == 0 \
            and (b * s) % mesh.devices.size == 0:
        ep_degree = int(mesh.devices.size)
        t_ep = _time_step(
            moe_loss("grouped_ep", ep_axes=("expert",), mesh=mesh,
                     kernel_interpret=True if on_cpu else None),
            params, x,
        )
        # the paired OVERLAP leg (ISSUE 10): same exchange split into
        # dispatch_chunks ppermute-ring chunks, double-buffered under
        # the grouped GEMMs. Same rows on the wire, same outputs —
        # on TPU the ratio vs the one-shot row above is the overlap
        # win; on the CPU mesh it is interpreter milliseconds (labeled)
        n_rows = (b * s) // mesh.devices.size * k
        if n_rows % chunks == 0:
            t_ep_chunked = _time_step(
                moe_loss("grouped_ep", ep_axes=("expert",), mesh=mesh,
                         kernel_interpret=True if on_cpu else None,
                         dispatch_chunks=chunks),
                params, x,
            )
    return {
        "config": {"batch": b, "seq": s, "d_model": d, "d_ff": f,
                   "experts": e, "top_k": k},
        "platform": jax.devices()[0].platform,
        "dense_iso_flops_ms": round(t_dense * 1e3, 3),
        "moe_gather_ms": round(t_gather * 1e3, 3),
        "moe_einsum_ms": round(t_einsum * 1e3, 3),
        # dispatch overhead over the iso-FLOPs floor (<0.15 = done bar)
        "gather_overhead": round((t_gather - t_dense) / t_dense, 3),
        "einsum_overhead": round((t_einsum - t_dense) / t_dense, 3),
        "gather_speedup_vs_einsum": round(t_einsum / t_gather, 2),
        **({} if t_grouped is None else {
            "moe_grouped_dropless_ms": round(t_grouped * 1e3, 3),
            "grouped_overhead": round((t_grouped - t_dense) / t_dense, 3),
        }),
        **({} if t_ep is None else {
            "moe_grouped_ep_ms": round(t_ep * 1e3, 3),
            "grouped_ep_degree": ep_degree,
            # True = Pallas interpreter on the CPU mesh: wiring proof
            # only, NOT comparable to hardware rows
            "grouped_ep_interpret": bool(ep_interpret),
        }),
        **({} if t_ep_chunked is None else {
            # the paired overlap-on leg (dispatch_chunks ppermute
            # ring); the overlap RATIO is a hardware number — on the
            # CPU mesh both legs measure the interpreter (labeled via
            # grouped_ep_interpret above)
            "moe_grouped_ep_chunked_ms": round(t_ep_chunked * 1e3, 3),
            "grouped_ep_dispatch_chunks": chunks,
            "grouped_ep_overlap_ratio": round(t_ep / t_ep_chunked, 3),
        }),
    }


def main():
    on_cpu = jax.devices()[0].platform == "cpu"
    configs = CONFIGS_CPU if on_cpu else CONFIGS
    if on_cpu and jax.device_count() < 2:
        print(json.dumps({"note": (
            "single CPU device: the grouped_ep row needs a device mesh"
            " — rerun with XLA_FLAGS=--xla_force_host_platform_device_"
            "count=8 to exercise it in interpret mode"
        )}), flush=True)
    for cfg in configs:
        print(json.dumps(bench_config(*cfg)), flush=True)


if __name__ == "__main__":
    main()
