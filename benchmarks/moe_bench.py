"""MoE dispatch cost: gather vs einsum dispatch vs iso-FLOPs dense FFN.

Substantiates the fast-dispatch claim (VERDICT r4 missing #2): the
reference delegates its MoE hot path to a custom CUDA backend because
one-hot dispatch dominates expert FLOPs
(``atorch/atorch/modules/moe/moe_layer.py:511`` fastmoe; all-to-all at
``:87``). On TPU the equivalent win comes from slot-gather dispatch
(``ops/moe._moe_compute_gather``): data movement O(T*D) instead of the
[T,E,C] einsums' capacity_factor*T^2*D FLOPs.

Measures fwd+bwd step time of
  - the MoE layer with dispatch="gather" (the default),
  - the MoE layer with dispatch="einsum" (the reference check),
  - a dense FFN with the same per-token FLOPs as the experts' matmuls
    (top_k * d_ff wide) — the iso-FLOPs floor,
and reports dispatch overhead = (moe - dense) / dense.

Run: ``python benchmarks/moe_bench.py`` (TPU host or CPU).
Prints one JSON line per config.
"""

from __future__ import annotations

import json
import os
import sys
import time

# repo-root import without PYTHONPATH (which breaks the tunneled TPU
# plugin's sitecustomize registration on this harness)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.ops.moe import MoEConfig, init_moe_params, moe_ffn

# (batch, seq, d_model, d_ff, num_experts, top_k)
CONFIGS = [
    (8, 1024, 1024, 2816, 8, 1),
    (8, 1024, 1024, 2816, 8, 2),
    (4, 2048, 2048, 5632, 8, 2),
]
# CPU can't push the TPU shapes through the einsum path in bounded time
# (the [T,E,C] einsums are ~170 GFLOPs per call at T=8k — that cost IS
# the finding); scaled-down shapes show the same overhead ratios
CONFIGS_CPU = [
    (2, 256, 256, 704, 8, 1),
    (2, 256, 256, 704, 8, 2),
    (1, 512, 512, 1408, 8, 2),
]
STEPS = 10


def _time_step(fn, *args):
    step = jax.jit(fn)
    jax.device_get(step(*args))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(STEPS):
        out = step(*args)
    # device_get of a dependent scalar: the only reliable sync on the
    # tunneled platform (see flash_bench.py)
    jax.device_get(out)
    return (time.perf_counter() - t0) / STEPS


def bench_config(b, s, d, f, e, k, dtype=jnp.bfloat16):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(b, s, d), dtype)
    params = jax.tree.map(
        lambda a: a.astype(dtype),
        init_moe_params(jax.random.PRNGKey(0), d, f, e),
    )

    def moe_loss(dispatch):
        cfg = MoEConfig(num_experts=e, top_k=k, dispatch=dispatch)

        def loss(p, x):
            o, aux, _ = moe_ffn(p, x, cfg, activation=jax.nn.silu)
            return jnp.sum(o.astype(jnp.float32) ** 2) + aux

        def step(p, x):
            l, g = jax.value_and_grad(loss)(p, x)
            return l + sum(
                jnp.sum(jnp.abs(a).astype(jnp.float32))
                for a in jax.tree.leaves(g)
            )

        return step

    # iso-FLOPs dense floor: each routed token does 2 matmuls of width
    # d_ff per chosen expert -> top_k * d_ff wide dense FFN
    wf = f * k
    dense_p = {
        "up": jnp.asarray(rng.randn(d, wf) / np.sqrt(d), dtype),
        "down": jnp.asarray(rng.randn(wf, d) / np.sqrt(wf), dtype),
    }

    def dense_step(p, x):
        def loss(p, x):
            h = jax.nn.silu(x @ p["up"])
            return jnp.sum((h @ p["down"]).astype(jnp.float32) ** 2)

        l, g = jax.value_and_grad(loss)(p, x)
        return l + sum(
            jnp.sum(jnp.abs(a).astype(jnp.float32))
            for a in jax.tree.leaves(g)
        )

    t_dense = _time_step(dense_step, dense_p, x)
    t_gather = _time_step(moe_loss("gather"), params, x)
    t_einsum = _time_step(moe_loss("einsum"), params, x)
    # the DROPLESS grouped kernel only times meaningfully on real
    # hardware — the CPU run would measure the Pallas interpreter, not
    # the kernel (correctness on CPU is tests/test_ops.py's job)
    t_grouped = (None if jax.devices()[0].platform == "cpu"
                 else _time_step(moe_loss("grouped"), params, x))
    return {
        "config": {"batch": b, "seq": s, "d_model": d, "d_ff": f,
                   "experts": e, "top_k": k},
        "platform": jax.devices()[0].platform,
        "dense_iso_flops_ms": round(t_dense * 1e3, 3),
        "moe_gather_ms": round(t_gather * 1e3, 3),
        "moe_einsum_ms": round(t_einsum * 1e3, 3),
        # dispatch overhead over the iso-FLOPs floor (<0.15 = done bar)
        "gather_overhead": round((t_gather - t_dense) / t_dense, 3),
        "einsum_overhead": round((t_einsum - t_dense) / t_dense, 3),
        "gather_speedup_vs_einsum": round(t_einsum / t_gather, 2),
        **({} if t_grouped is None else {
            "moe_grouped_dropless_ms": round(t_grouped * 1e3, 3),
            "grouped_overhead": round((t_grouped - t_dense) / t_dense, 3),
        }),
    }


def main():
    configs = (CONFIGS_CPU if jax.devices()[0].platform == "cpu"
               else CONFIGS)
    for cfg in configs:
        print(json.dumps(bench_config(*cfg)), flush=True)


if __name__ == "__main__":
    main()
