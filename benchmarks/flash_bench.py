"""Head-to-head: the in-tree Pallas flash attention vs the stock jax
TPU kernel (``jax.experimental.pallas.ops.tpu.flash_attention``).

Substantiates docs/parallelism.md's kernel claim with a measured number
at the bench shapes. Forward+backward (grad wrt q, k, v), causal, bf16.

Run on the TPU host: ``python benchmarks/flash_bench.py``
Prints one JSON line per shape.
"""

from __future__ import annotations

import json
import os
import sys
import time

# repo-root import without PYTHONPATH (which breaks the tunneled TPU
# plugin's sitecustomize registration on this harness)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

SHAPES = [
    # (batch, heads, kv_heads, seq, head_dim)  — the two bench configs
    (16, 20, 20, 1024, 128),
    (8, 20, 20, 2048, 128),
    (1, 16, 16, 16384, 128),  # long-context preset shape
]
STEPS = 10


def _inputs(b, h, hkv, s, d, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, hkv, s, d), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, hkv, s, d), jnp.bfloat16)
    return q, k, v


def _time_fwd_bwd(fn, q, k, v):
    def scalar(q, k, v):
        # one program: fwd + bwd, reduced to ONE scalar so the sync is a
        # cheap device_get (on the tunneled platform block_until_ready
        # can return before the remote executable finishes — device_get
        # of a dependent value is the only reliable sync, and a scalar
        # keeps the transfer out of the measurement)
        loss, grads = jax.value_and_grad(
            lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2),
        )(q, k, v)
        return loss + sum(
            jnp.sum(jnp.abs(g).astype(jnp.float32)) for g in grads
        )

    step = jax.jit(scalar)
    jax.device_get(step(q, k, v))  # compile + warm
    t0 = time.perf_counter()
    out = None
    for _ in range(STEPS):
        out = step(q, k, v)
    jax.device_get(out)  # device queue is FIFO: waits for all steps
    return (time.perf_counter() - t0) / STEPS


def main() -> int:
    from jax.experimental.pallas.ops.tpu import flash_attention as stock

    from dlrover_tpu.ops.flash_attention import flash_attention

    for b, h, hkv, s, d in SHAPES:
        q, k, v = _inputs(b, h, hkv, s, d)
        block_q = min(1024, s)
        ours_t = _time_fwd_bwd(
            lambda q, k, v: flash_attention(
                q, k, v, True, block_q=block_q, block_k=min(1024, s)
            ),
            q, k, v,
        )
        scale = 1.0 / (d ** 0.5)
        # fairness: the stock kernel gets BOTH its library defaults and
        # the same 1024-tile configuration ours runs; best-of wins
        bs = min(1024, s)
        tuned = stock.BlockSizes(
            block_q=bs, block_k_major=bs, block_k=bs, block_b=1,
            block_q_major_dkv=bs, block_k_major_dkv=bs, block_k_dkv=bs,
            block_q_dkv=bs, block_k_major_dq=bs, block_k_dq=bs,
            block_q_dq=bs,
        )
        stock_times = {}
        for name, sizes in (("default", None), ("tuned1024", tuned)):
            try:
                stock_times[name] = _time_fwd_bwd(
                    lambda q, k, v: stock.flash_attention(
                        q, k, v, causal=True, sm_scale=scale,
                        block_sizes=sizes,
                    ),
                    q, k, v,
                )
            except Exception as e:  # noqa: BLE001 — config infeasible
                stock_times[name] = float("inf")
                print(f"# stock {name} failed: {e}"[:160])
        stock_best = min(stock_times, key=stock_times.get)
        stock_t = stock_times[stock_best]
        stock_ok = stock_t != float("inf")
        print(json.dumps({
            "metric": "flash_attention_vs_stock",
            "shape": f"b{b}h{h}s{s}d{d}",
            "ours_ms": round(ours_t * 1e3, 2),
            # null, not Infinity: the line must stay valid JSON even
            # when every stock config fails on this shape
            "stock_ms": round(stock_t * 1e3, 2) if stock_ok else None,
            "stock_best_config": stock_best if stock_ok else None,
            "speedup": round(stock_t / ours_t, 3) if stock_ok else None,
        }))

    if os.environ.get("FLASH_BENCH_MASKS", "1") == "1":
        _mask_variants()
    return 0


def _mask_variants():
    """Fused masking vs materialized bias: the segmented (packed) and
    prefix-LM kernels against the XLA reference with an additive S x S
    bias — the memory/time cost the fused masks exist to remove."""
    from dlrover_tpu.ops.flash_attention import (
        flash_attention_prefix,
        flash_attention_segmented,
        segmented_attention,
    )

    for b, h, hkv, s, d in SHAPES:
        q, k, v = _inputs(b, h, hkv, s, d)
        bq, bk = min(1024, s), min(1024, s)

        # packed: 4 documents per row, uneven boundaries
        seg_np = np.sort(
            np.random.RandomState(1).randint(0, 4, (b, s)), axis=1
        ).astype(np.int32)
        seg = jnp.asarray(seg_np)
        seg_t = _time_fwd_bwd(
            lambda q, k, v: flash_attention_segmented(
                q, k, v, seg, True, block_q=bq, block_k=bk),
            q, k, v,
        )
        try:
            # the PRODUCTION bias dispatch (use_flash=False), not a
            # hand-rolled replica — this is exactly what the fused
            # kernel replaces; everything (incl. the S x S bias its
            # trace materializes) stays inside the try, since that
            # allocation is the thing expected to blow up at long S
            bias_t = _time_fwd_bwd(
                lambda q, k, v: segmented_attention(
                    q, k, v, seg, use_flash=False),
                q, k, v,
            )
        except Exception as e:  # noqa: BLE001 — S x S bias can OOM
            bias_t = None
            print(f"# bias path failed (expected at long S): {e}"[:160])
        print(json.dumps({
            "metric": "segmented_fused_vs_bias",
            "shape": f"b{b}h{h}s{s}d{d}",
            "fused_ms": round(seg_t * 1e3, 2),
            "bias_ms": round(bias_t * 1e3, 2) if bias_t else None,
            "speedup": round(bias_t / seg_t, 3) if bias_t else None,
        }))

        # prefix-LM: prompt = S/4
        prefix = jnp.full((b,), s // 4, jnp.int32)
        pre_t = _time_fwd_bwd(
            lambda q, k, v: flash_attention_prefix(
                q, k, v, prefix, block_q=bq, block_k=bk),
            q, k, v,
        )
        print(json.dumps({
            "metric": "prefix_fused",
            "shape": f"b{b}h{h}s{s}d{d}",
            "fused_ms": round(pre_t * 1e3, 2),
        }))


if __name__ == "__main__":
    sys.exit(main())
