"""Micro-benchmark: the native shm ring vs in-process batch building.

Round-2 verdict #8 asked for the number that justifies
``native/src/shm_ring.cc`` (the reference's shm path exists because it
measurably removed a bottleneck, ``atorch/data/shm_context.py:20``).

Model of the workload: each training step the accelerator is busy for
``step_s`` (the process just *waits* on it — on TPU that's the dispatch
of the next jitted step), and building the next batch costs ``prep_s``
of host CPU (tokenization/augmentation).

  in-process : prep and step serialize          -> ~1/(prep+step) steps/s
  shm ring   : coworker processes prep while the
               trainer waits on the device      -> ~1/max(prep, step)

Run: ``python benchmarks/shm_ring_bench.py`` — prints one JSON line.
The committed numbers live in ``docs/data_pipeline.md``.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

BATCH_ROWS = 8
SEQ = 2048
# host preprocessing per batch; prep ~= step (SHM_BENCH_PREP_MS=24) is
# the regime coworker feeding exists for (ideal -> ~2x)
PREP_MS_TARGET = float(os.environ.get("SHM_BENCH_PREP_MS", "15"))
STEP_MS = 25.0  # simulated device-bound step (process waits)
N_BATCHES = int(os.environ.get("SHM_BENCH_BATCHES", "200"))
N_WORKERS = 2


def _calibrate_prep(target_ms: float) -> int:
    """Find the work size that costs ~target_ms on this host (scale by
    the measured per-element cost; median of 3 to resist scheduler
    noise — a mis-calibrated prep silently rescales the whole ideal)."""
    n = max(1 << 15, BATCH_ROWS * (SEQ + 1))
    for _ in range(10):
        samples = []
        for _ in range(3):
            t0 = time.perf_counter()
            _prep_batch(0, n)
            samples.append((time.perf_counter() - t0) * 1e3)
        dt = sorted(samples)[1]
        if 0.85 * target_ms <= dt <= 1.25 * target_ms:
            return n
        n = max(
            BATCH_ROWS * (SEQ + 1),
            min(int(n * target_ms / max(dt, 0.1)), 1 << 24),
        )
    return n


def _prep_batch(seed: int, work: int):
    """Tokenization-shaped CPU work: hashing, sorting, bincount."""
    rng = np.random.RandomState(seed)
    raw = rng.randint(0, 1 << 30, size=work).astype(np.uint32)
    tok = (raw * np.uint32(2654435761)) >> np.uint32(18)
    order = np.argsort(tok, kind="stable")
    counts = np.bincount(tok[order] & 1023, minlength=1024)
    del counts
    ids = (tok[: BATCH_ROWS * (SEQ + 1)] % 32000).astype(np.int32)
    ids = ids.reshape(BATCH_ROWS, SEQ + 1)
    return {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}


def _device_step():
    """The accelerator is busy; the host only waits (releases the GIL /
    the CPU entirely, like a real dispatch+sync on TPU)."""
    time.sleep(STEP_MS / 1e3)


def bench_in_process(work: int) -> float:
    t0 = time.perf_counter()
    for i in range(N_BATCHES):
        batch = _prep_batch(i, work)
        assert batch["input_ids"].shape == (BATCH_ROWS, SEQ)
        _device_step()
    return N_BATCHES / (time.perf_counter() - t0)


def _producer(worker_rank: int, num_workers: int):
    work = int(__import__("os").environ["SHM_BENCH_WORK"])
    for i in range(worker_rank, N_BATCHES, num_workers):
        yield _prep_batch(i, work)


def bench_shm_ring(work: int):
    """Returns (steady_steps_per_s, warmup_s).

    Steady state is timed from the FIRST yielded batch: coworker spawn
    (python + numpy import, ~1 s/process) happens once per job and
    amortizes over thousands of training steps, so folding it into a
    200-batch window would mismeasure the regime the ring exists for.
    It is still reported (``warmup_s``) — a job short enough that spawn
    dominates should not use coworker feeding at all."""
    import os

    from dlrover_tpu.trainer.shm_dataloader import ShmDataLoader

    os.environ["SHM_BENCH_WORK"] = str(work)
    slot_bytes = BATCH_ROWS * (SEQ + 1) * 4 * 2 + 4096
    t_create = time.perf_counter()
    loader = ShmDataLoader(
        _producer, num_workers=N_WORKERS, slot_bytes=slot_bytes,
        n_slots=4,
    )
    n = 0
    t0 = warmup = None
    with loader:
        for batch in loader:
            if t0 is None:
                t0 = time.perf_counter()
                warmup = t0 - t_create
            assert batch["input_ids"].shape == (BATCH_ROWS, SEQ)
            n += 1
            _device_step()
    if t0 is None:
        raise RuntimeError(
            "no batches arrived — producer processes died "
            "(stdin-run parents cannot spawn; run as a script)"
        )
    elapsed = time.perf_counter() - t0
    assert n == N_BATCHES, f"consumed {n} of {N_BATCHES}"
    # the first batch's own prep is outside the timed window; the other
    # N-1 steps are steady-state pipeline
    return (n - 1) / elapsed, warmup


def main() -> int:
    work = _calibrate_prep(PREP_MS_TARGET)
    # median of 3: the reported prep_ms scales ideal_overlap_speedup,
    # the benchmark's denominator — a single noisy sample would skew it
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        _prep_batch(0, work)
        samples.append((time.perf_counter() - t0) * 1e3)
    prep_ms = sorted(samples)[1]

    inproc = bench_in_process(work)
    shm, warmup_s = bench_shm_ring(work)
    # the blocked-on-device regime's ceiling: prep fully hidden behind
    # the device step (valid on ANY core count — the consumer is not on
    # the CPU while the device runs)
    ideal = (prep_ms + STEP_MS) / max(prep_ms, STEP_MS)
    print(json.dumps({
        "metric": "shm_ring_speedup",
        "value": round(shm / inproc, 3),
        "unit": "x",
        "detail": {
            "in_process_steps_per_s": round(inproc, 2),
            "shm_ring_steps_per_s": round(shm, 2),
            "ideal_overlap_speedup": round(ideal, 3),
            "coworker_spawn_warmup_s": round(warmup_s, 2),
            "prep_ms_per_batch": round(prep_ms, 1),
            "simulated_step_ms": STEP_MS,
            "num_coworkers": N_WORKERS,
            "batches": N_BATCHES,
        },
    }))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
