"""Pretrain a Llama-family model elastically.

Run standalone on any host (CPU mesh for a smoke test, TPU in prod):

    # 8 virtual CPU devices, tiny model
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/train_llama.py --preset tiny --steps 20

    # under the elastic launcher (master-backed rendezvous, failover)
    python -m dlrover_tpu.trainer.run --standalone --nnodes 1 \\
        examples/train_llama.py --preset tiny --steps 20

Role parity: the reference's ``examples/pytorch/llama2`` training scripts.
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.models import llama
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.trainer.conf import build_configuration
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.executor import TrainExecutor


def synthetic_batches(vocab_size, batch, seq, seed=0):
    rng = np.random.RandomState(seed)

    def gen():
        while True:
            ids = rng.randint(0, vocab_size, size=(batch, seq + 1))
            yield {
                "input_ids": jnp.asarray(ids[:, :-1]),
                "labels": jnp.asarray(ids[:, 1:]),
            }

    return gen


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="tiny", choices=["tiny", "1b", "7b"])
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=0, help="0 = preset default")
    p.add_argument("--ckpt_dir", default="")
    p.add_argument("--moe_experts", type=int, default=0)
    p.add_argument("--ring", type=int, default=0,
                   help="sequence-parallel ring size (long context): "
                        "adds a 'seq' mesh axis and runs ring "
                        "attention, e.g. --ring 2 --seq 512 on the "
                        "8-device CPU mesh")
    args = p.parse_args()

    if args.preset == "tiny":
        config = llama.llama_tiny(num_experts=args.moe_experts)
        seq = args.seq or 128
    elif args.preset == "1b":
        config = llama.llama2_7b(
            hidden_size=2048, intermediate_size=5504, num_layers=16,
            num_heads=16, num_kv_heads=16,
            param_dtype=jnp.bfloat16, compute_dtype=jnp.bfloat16,
            num_experts=args.moe_experts,
        )
        seq = args.seq or 2048
    else:
        config = llama.llama2_7b(num_experts=args.moe_experts)
        seq = args.seq or 4096

    n = jax.device_count()
    ring = max(1, args.ring)
    # fsdp only when devices remain after the ring axis takes its share
    fsdp = 2 if n >= 4 * ring else 1
    plan = MeshPlan(data=-1, fsdp=fsdp, seq=ring)
    if ring > 1:
        # long context: the model runs ring attention over the "seq"
        # axis. Only the AXIS NAME goes on the config — the mesh itself
        # is picked up ambiently from whatever accelerate builds, so an
        # elastic world change (which re-runs accelerate over the new
        # devices) keeps working.
        from dataclasses import replace

        config = replace(config, seq_axis="seq")
    strategy = Strategy(
        mesh=plan,
        rule_set="moe" if args.moe_experts else "llama",
        remat_policy="",  # the model remats per layer internally
    )
    batches = synthetic_batches(config.vocab_size, args.batch, seq)
    trainer = ElasticTrainer(
        llama.make_init_fn(config),
        llama.make_loss_fn(config),
        optax.adamw(3e-4, weight_decay=0.1),
        next(batches()),
        strategy=strategy,
        ckpt_dir=args.ckpt_dir,
    )
    executor = TrainExecutor(
        trainer,
        train_iter_fn=batches,
        conf=build_configuration({
            "train_steps": args.steps, "log_every_steps": 10,
        }),
    )
    out = executor.train_and_evaluate()
    print(f"finished at step {out['step']} "
          f"({llama.param_count(config) / 1e6:.1f}M params, "
          f"{n} devices)")


if __name__ == "__main__":
    main()
