"""Pretrain a Llama-family model elastically.

Run standalone on any host (CPU mesh for a smoke test, TPU in prod):

    # 8 virtual CPU devices, tiny model
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/train_llama.py --preset tiny --steps 20

    # under the elastic launcher (master-backed rendezvous, failover)
    python -m dlrover_tpu.trainer.run --standalone --nnodes 1 \\
        examples/train_llama.py --preset tiny --steps 20

Role parity: the reference's ``examples/pytorch/llama2`` training scripts.
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.models import llama
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.trainer.conf import build_configuration
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.executor import TrainExecutor


def synthetic_batches(vocab_size, batch, seq, seed=0):
    rng = np.random.RandomState(seed)

    def gen():
        while True:
            ids = rng.randint(0, vocab_size, size=(batch, seq + 1))
            yield {
                "input_ids": jnp.asarray(ids[:, :-1]),
                "labels": jnp.asarray(ids[:, 1:]),
            }

    return gen


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="tiny", choices=["tiny", "1b", "7b"])
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=0, help="0 = preset default")
    p.add_argument("--layers", type=int, default=0,
                   help="override the preset's layer count (e.g. 6 for "
                        "an uneven --pipe 2 --pipe_virtual 2 demo)")
    p.add_argument("--ckpt_dir", default="")
    p.add_argument("--moe_experts", type=int, default=0)
    p.add_argument("--ring", type=int, default=0,
                   help="sequence-parallel ring size (long context): "
                        "adds a 'seq' mesh axis and runs ring "
                        "attention, e.g. --ring 2 --seq 512 on the "
                        "8-device CPU mesh")
    p.add_argument("--pipe", type=int, default=0,
                   help="pipeline stages: adds a 'pipe' mesh axis and "
                        "runs the decoder as a GPipe/interleaved "
                        "pipeline, e.g. --pipe 2 on the 8-device mesh. "
                        "NB: the pipelined MoE loss does not surface "
                        "the per-step load-balance metrics the plain "
                        "path reports (apply_pipelined has no metrics "
                        "output)")
    p.add_argument("--pipe_virtual", type=int, default=1,
                   help="virtual stages per physical stage (V>1 = "
                        "circular interleaved schedule)")
    p.add_argument("--pipe_depths", default="",
                   help="comma-separated per-chunk layer counts in "
                        "visit order (uneven stage split; default: "
                        "planner-balanced via plan_stage_depths)")
    args = p.parse_args()
    if args.pipe and args.ring:
        p.error("--pipe and --ring compose via a custom Strategy; this "
                "example drives one at a time")
    if args.pipe_virtual < 1:
        p.error(f"--pipe_virtual must be >= 1 (got {args.pipe_virtual})")

    layer_kw = {"num_layers": args.layers} if args.layers else {}
    if args.preset == "tiny":
        config = llama.llama_tiny(num_experts=args.moe_experts,
                                  **layer_kw)
        seq = args.seq or 128
    elif args.preset == "1b":
        config = llama.llama2_7b(
            hidden_size=2048, intermediate_size=5504,
            num_heads=16, num_kv_heads=16,
            param_dtype=jnp.bfloat16, compute_dtype=jnp.bfloat16,
            num_experts=args.moe_experts,
            num_layers=args.layers or 16,
        )
        seq = args.seq or 2048
    else:
        config = llama.llama2_7b(num_experts=args.moe_experts,
                                 **layer_kw)
        seq = args.seq or 4096

    n = jax.device_count()
    ring = max(1, args.ring)
    pipe = max(1, args.pipe)
    # fsdp only when devices remain after the ring/pipe axes take theirs
    fsdp = 2 if n >= 4 * ring * pipe else 1
    plan = MeshPlan(data=-1, fsdp=fsdp, seq=ring, pipe=pipe)
    if ring > 1:
        # long context: the model runs ring attention over the "seq"
        # axis. Only the AXIS NAME goes on the config — the mesh itself
        # is picked up ambiently from whatever accelerate builds, so an
        # elastic world change (which re-runs accelerate over the new
        # devices) keeps working.
        from dataclasses import replace

        config = replace(config, seq_axis="seq")
    stage_depths = None
    if pipe > 1:
        if args.pipe_depths:
            stage_depths = tuple(
                int(d) for d in args.pipe_depths.split(",")
            )
        elif config.num_layers % (args.pipe_virtual * pipe):
            # indivisible layer count: planner-balanced uneven split
            from dlrover_tpu.parallel.planner import plan_stage_depths

            stage_depths = plan_stage_depths(
                [1.0] * config.num_layers, pipe, args.pipe_virtual
            )
    strategy = Strategy(
        mesh=plan,
        # llama_pp carries both the pipe-leading layer rules and the
        # expert submesh rules, so pipelined MoE resolves to it too
        rule_set=("llama_pp" if pipe > 1
                  else ("moe" if args.moe_experts else "llama")),
        remat_policy="",  # the model remats per layer internally
        num_virtual=args.pipe_virtual,
        stage_depths=stage_depths,
    )
    if pipe > 1:
        from dlrover_tpu.models.losses import masked_lm_loss

        num_mb = 2 * pipe

        def loss_fn(params, batch, rng):
            logits, aux = llama.apply_pipelined(
                params, batch["input_ids"], config,
                num_stages=pipe, num_microbatches=num_mb, rng=rng,
                num_virtual=strategy.num_virtual,
                stage_depths=strategy.stage_depths,
            )
            loss = masked_lm_loss(logits, batch["labels"])
            if config.num_experts > 0:
                # aux sums over microbatches as well as layers
                loss = loss + config.moe_aux_weight * aux / (
                    max(1, config.num_layers) * num_mb
                )
            return loss, {}
    else:
        loss_fn = llama.make_loss_fn(config)
    batches = synthetic_batches(config.vocab_size, args.batch, seq)
    trainer = ElasticTrainer(
        llama.make_init_fn(config),
        loss_fn,
        optax.adamw(3e-4, weight_decay=0.1),
        next(batches()),
        strategy=strategy,
        ckpt_dir=args.ckpt_dir,
    )
    executor = TrainExecutor(
        trainer,
        train_iter_fn=batches,
        conf=build_configuration({
            "train_steps": args.steps, "log_every_steps": 10,
        }),
    )
    out = executor.train_and_evaluate()
    print(f"finished at step {out['step']} "
          f"({llama.param_count(config) / 1e6:.1f}M params, "
          f"{n} devices)")


if __name__ == "__main__":
    main()
