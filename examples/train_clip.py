"""Contrastive image-text pretraining (CLIP) on synthetic pairs.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/train_clip.py --steps 20
"""

import argparse

import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.models import clip
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.trainer.conf import build_configuration
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.executor import TrainExecutor


def pair_batches(config, batch, seed=0):
    rng = np.random.RandomState(seed)
    size = config.image_size

    def gen():
        while True:
            yield {
                "input_ids": jnp.asarray(rng.randint(
                    0, config.vocab_size, (batch, config.max_text_len)
                )),
                "pixel_values": jnp.asarray(
                    rng.rand(batch, size, size, 3), jnp.float32
                ),
            }

    return gen


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="tiny", choices=["tiny", "base"])
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--batch", type=int, default=8)
    args = p.parse_args()

    config = (clip.clip_tiny if args.preset == "tiny" else clip.clip_base)()
    batches = pair_batches(config, args.batch)
    trainer = ElasticTrainer(
        clip.make_init_fn(config),
        clip.make_loss_fn(config),
        optax.adamw(1e-4),
        next(batches()),
        strategy=Strategy(mesh=MeshPlan(data=-1), rule_set="clip",
                          remat_policy=""),
    )
    executor = TrainExecutor(
        trainer, train_iter_fn=batches,
        conf=build_configuration({"train_steps": args.steps,
                                  "log_every_steps": 10}),
    )
    out = executor.train_and_evaluate()
    print(f"finished at step {out['step']}")


if __name__ == "__main__":
    main()
