"""Masked-LM pretraining for the BERT family (synthetic data).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/train_bert_mlm.py --steps 20
"""

import argparse

import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.models import bert
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.trainer.conf import build_configuration
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.executor import TrainExecutor


def mlm_batches(vocab_size, batch, seq, mask_prob=0.15, seed=0):
    rng = np.random.RandomState(seed)

    def gen():
        while True:
            ids = rng.randint(4, vocab_size, size=(batch, seq))
            mask = rng.rand(batch, seq) < mask_prob
            labels = np.where(mask, ids, -100)
            inputs = np.where(mask, 3, ids)  # 3 = [MASK]
            yield {
                "input_ids": jnp.asarray(inputs),
                "labels": jnp.asarray(labels),
            }

    return gen


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="tiny",
                   choices=["tiny", "base", "large"])
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=64)
    args = p.parse_args()

    config = {
        "tiny": bert.bert_tiny, "base": bert.bert_base,
        "large": bert.bert_large,
    }[args.preset]()
    batches = mlm_batches(config.vocab_size, args.batch, args.seq)
    trainer = ElasticTrainer(
        bert.make_init_fn(config),
        bert.make_mlm_loss_fn(config),
        optax.adamw(1e-4),
        next(batches()),
        strategy=Strategy(mesh=MeshPlan(data=-1), rule_set="bert",
                          remat_policy=""),
    )
    executor = TrainExecutor(
        trainer, train_iter_fn=batches,
        conf=build_configuration({"train_steps": args.steps,
                                  "log_every_steps": 10}),
    )
    out = executor.train_and_evaluate()
    print(f"finished at step {out['step']}")


if __name__ == "__main__":
    main()
