"""Criteo-style DeepFM with the dynamic data-shard service (BASELINE
config #4): the master dispatches index shards on demand, so fast
workers get more data and a resumed job continues mid-epoch.

    # plain: boots an in-process local master (shard service only)
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/train_deepfm_sharded.py --steps 40

    # under the elastic launcher the master comes from the env contract
    python -m dlrover_tpu.trainer.run --standalone --nnodes 1 \\
        examples/train_deepfm_sharded.py --steps 40

Role parity: the reference's DeepRec/Criteo PS jobs fed by
``ShardingClient`` (``dlrover/python/elastic_agent/sharding/client.py``)
— here the consumption loop is identical, the training step is a jitted
SPMD program instead of a PS session.
"""

import argparse
import os

import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.agent.sharding_client import ShardingClient
from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.models import deepfm
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.trainer.conf import build_configuration
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.executor import (
    ElasticDataShardReportHook,
    TrainExecutor,
)


def synth_criteo_batch(config, index_lo, index_hi, seed=0):
    """Deterministic synthetic rows for [index_lo, index_hi): the shard
    indices ARE the dataset — any worker renders the same records."""
    rng = np.random.RandomState(seed + index_lo)
    n = index_hi - index_lo
    sparse = rng.randint(
        0, config.vocab_size, size=(n, config.num_sparse_features)
    )
    dense = rng.rand(n, config.num_dense_features).astype(np.float32)
    label = (rng.rand(n) < 0.25).astype(np.int32)
    return {
        "sparse": jnp.asarray(sparse),
        "dense": jnp.asarray(dense),
        "label": jnp.asarray(label),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--dataset_size", type=int, default=65536)
    p.add_argument("--epochs", type=int, default=1)
    args = p.parse_args()

    config = deepfm.deepfm_tiny()

    # master: from the agent env contract under tpurun, else in-process
    local_master = None
    addr = os.environ.get(NodeEnv.MASTER_ADDR, "")
    if addr:
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(addr, node_id=int(
            os.environ.get(NodeEnv.NODE_ID, "0")
        ))
    else:
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.master.local_master import start_local_master

        local_master = start_local_master()
        client = MasterClient(local_master.addr, node_id=0)

    sharding = ShardingClient(
        client, "criteo_synth", batch_size=args.batch,
        dataset_size=args.dataset_size, num_epochs=args.epochs,
        shuffle=False, num_minibatches_per_shard=4,
    )

    def shard_batches():
        """Dynamic consumption: ask the master for the next index shard,
        render its records, emit per-batch slices."""
        while True:
            shard = sharding.fetch_shard()
            if shard is None:
                return  # dataset exhausted (across all epochs)
            for lo in range(shard.start, shard.end, args.batch):
                hi = min(lo + args.batch, shard.end)
                if hi - lo == args.batch:  # fixed shapes for jit
                    yield synth_criteo_batch(config, lo, hi)

    trainer = ElasticTrainer(
        deepfm.make_init_fn(config),
        deepfm.make_loss_fn(config),
        optax.adagrad(0.05),
        synth_criteo_batch(config, 0, args.batch),
        strategy=Strategy(mesh=MeshPlan(data=-1)),
        master_client=client,
    )
    executor = TrainExecutor(
        trainer,
        train_iter_fn=shard_batches,
        hooks=[ElasticDataShardReportHook(sharding, args.batch)],
        conf=build_configuration({
            "train_steps": args.steps, "log_every_steps": 10,
        }),
    )
    out = executor.train_and_evaluate()
    print(f"finished at step {out['step']}")
    if local_master is not None:
        local_master.stop()


if __name__ == "__main__":
    main()
