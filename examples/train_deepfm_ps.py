"""DeepFM on the asynchronous parameter-server path (DeepRec parity).

The full reference PS topology in one process: a local master (shard
service + PS cluster versioning), N PS shard servers applying adagrad
server-side, and W async workers that fetch **dynamic data shards** from
the master and push/pull parameters — no barrier between workers, global
batch emergent, exactly the reference's DeepRec CPU PS job shape
(``docs/blogs/deeprec_autoscale_cn.md``).

    JAX_PLATFORMS=cpu python examples/train_deepfm_ps.py --steps 60

Role parity: estimator PS training driven by ``ShardingClient``
(``dlrover/python/elastic_agent/sharding/client.py``) with the PS engine
swapped from TF runtime to ``dlrover_tpu.ps``.
"""

import argparse
import threading

import jax
import numpy as np

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.sharding_client import ShardingClient
from dlrover_tpu.master.local_master import start_local_master
from dlrover_tpu.models import deepfm
from dlrover_tpu.ps.client import PsClusterClient
from dlrover_tpu.ps.server import start_ps_shard
from dlrover_tpu.ps.trainer import AsyncPsTrainer


def synth_batch(config, lo, hi, seed=0):
    rng = np.random.RandomState(seed + lo)
    n = hi - lo
    sparse = rng.randint(0, config.vocab_size,
                         size=(n, config.num_sparse_features))
    dense = rng.rand(n, config.num_dense_features).astype(np.float32)
    # learnable labels: tied to a fixed projection of the features
    w = np.linspace(-1, 1, config.num_dense_features, dtype=np.float32)
    label = ((dense @ w) > 0).astype(np.float32)
    return {"sparse": sparse, "dense": dense, "label": label}


def worker_loop(worker_id, master_addr, config, batch_size, results):
    mc = MasterClient(master_addr, node_id=worker_id)
    cluster = PsClusterClient.discover(mc, num_shards=None)
    base_loss = deepfm.make_loss_fn(config)

    def loss_fn(params, batch):
        loss, _metrics = base_loss(params, batch, None)
        return loss

    trainer = AsyncPsTrainer(loss_fn, cluster, master_client=mc)
    params0 = deepfm.init(jax.random.PRNGKey(0), config)
    trainer.init_params(params0)  # idempotent across workers

    shard_client = ShardingClient(
        mc, dataset_name="criteo_ps", batch_size=batch_size,
        num_epochs=2, dataset_size=batch_size * 64,
        num_minibatches_per_shard=2,
    )
    losses = []
    while True:
        shard = shard_client.fetch_shard()
        if shard is None:
            break
        for blo in range(shard.start, shard.end, batch_size):
            batch = synth_batch(config, blo, min(blo + batch_size, shard.end))
            losses.append(trainer.step(batch))
            shard_client.report_batch_done()
        shard_client.report_task_done()
    results[worker_id] = losses
    cluster.close()
    mc.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ps", type=int, default=2)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    config = deepfm.deepfm_tiny()
    master = start_local_master()
    owner = MasterClient(master.addr, node_id=99)
    shards = [start_ps_shard(i, master_client=owner, optimizer="adagrad:0.1",
                             num_shards=args.ps)
              for i in range(args.ps)]
    try:
        results = {}
        threads = [
            threading.Thread(target=worker_loop, args=(
                w, master.addr, config, args.batch_size, results))
            for w in range(args.workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for w, losses in sorted(results.items()):
            if losses:
                print(f"worker {w}: {len(losses)} async steps, "
                      f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")
            else:
                print(f"worker {w}: 0 async steps (shard queue drained)")
    finally:
        for s in shards:
            s.stop()
        owner.close()
        master.stop()


if __name__ == "__main__":
    main()
