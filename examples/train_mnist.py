"""MNIST CNN smoke training (BASELINE config #1): the classic
``dlrover-run`` elastic-agent hello-world, on the JAX stack.

    # plain single process
    JAX_PLATFORMS=cpu python examples/train_mnist.py --steps 30

    # the full elastic stack: local master subprocess, agent,
    # rendezvous, worker spawn, monitoring
    python -m dlrover_tpu.trainer.run --standalone --nnodes 1 \\
        examples/train_mnist.py --steps 30

Role parity: ``dlrover/examples/pytorch/mnist`` +
``dlrover-run --standalone``.
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.models import mnist_cnn
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.trainer.conf import build_configuration
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.executor import TrainExecutor


def synthetic_mnist(batch, seed=0):
    rng = np.random.RandomState(seed)

    def gen():
        while True:
            images = rng.rand(batch, 28, 28, 1).astype(np.float32)
            labels = rng.randint(0, 10, size=(batch,))
            yield {
                "image": jnp.asarray(images),
                "label": jnp.asarray(labels),
            }

    return gen


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch", type=int, default=64)
    args = p.parse_args()

    batches = synthetic_mnist(args.batch)
    trainer = ElasticTrainer(
        mnist_cnn.make_init_fn(),
        mnist_cnn.make_loss_fn(),
        optax.sgd(0.1, momentum=0.9),
        next(batches()),
        strategy=Strategy(mesh=MeshPlan(data=-1)),
    )
    executor = TrainExecutor(
        trainer,
        train_iter_fn=batches,
        conf=build_configuration({
            "train_steps": args.steps, "log_every_steps": 10,
        }),
    )
    out = executor.train_and_evaluate()
    print(f"finished at step {out['step']} on "
          f"{jax.device_count()} devices")


if __name__ == "__main__":
    main()
