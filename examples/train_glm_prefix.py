"""GLM prefix-LM fine-tuning (instruction/response shape).

Each record is a prompt + response; the prompt is bidirectionally
visible (GLM's prefix mask, fused into the Pallas kernel on the flash
path), the response is generated causally with 2D block positions, and
the loss covers only response tokens (a fixed synthetic batch, overfit
as a demo — see train_neox_text.py for the shard-service data path).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/train_glm_prefix.py --steps 25

Role parity: the reference's GLM support (Megatron-sharded GLM blocks +
``fa2_with_glm_mask``) exercised as a training recipe.
"""

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.models import glm
from dlrover_tpu.parallel.accelerate import accelerate
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy


def synth_instruction_batch(vocab, batch, seq, seed):
    """Prompt of random length, response echoing a transformed prompt —
    learnable structure so the loss visibly falls."""
    rng = np.random.RandomState(seed)
    ids = np.zeros((batch, seq), np.int64)
    prefix = rng.randint(4, seq // 2, size=(batch,))
    labels = np.full((batch, seq), -100, np.int64)
    for b in range(batch):
        p = prefix[b]
        prompt = rng.randint(2, vocab, size=(p,))
        ids[b, :p] = prompt
        n = min(seq - p, p)
        response = (prompt[:n] + 1) % vocab  # the learnable mapping
        ids[b, p:p + n] = response
        # loss on response tokens only (predict token t at t-1)
        labels[b, p - 1:p + n - 1] = ids[b, p:p + n]
    return {
        "input_ids": jnp.asarray(ids),
        "labels": jnp.asarray(labels),
        "prefix_len": jnp.asarray(prefix, jnp.int32),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=25)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=64)
    args = p.parse_args()
    if args.seq < 10:
        p.error("--seq must be >= 10 (prompts span 4..seq/2 tokens)")

    # flash_interpret stays at the config default (None): it resolves
    # to the Mosaic kernel on TPU and the interpreter elsewhere
    cfg = glm.glm_tiny(max_seq_len=args.seq, use_flash=True)

    batch = synth_instruction_batch(cfg.vocab_size, args.batch,
                                    args.seq, seed=0)
    result = accelerate(
        glm.make_init_fn(cfg), glm.make_loss_fn(cfg),
        optax.adam(2e-3), batch,
        strategy=Strategy(mesh=MeshPlan(data=-1), rule_set="glm"),
    )
    state = result.init_fn(jax.random.PRNGKey(0))

    client = None
    addr = os.environ.get(NodeEnv.MASTER_ADDR, "")
    if addr:
        client = MasterClient(addr, node_id=int(
            os.environ.get(NodeEnv.NODE_ID, "0")))

    losses = []
    sharded = result.shard_batch(batch)
    for step in range(args.steps):
        state, m = result.train_step(state, sharded,
                                     jax.random.PRNGKey(step))
        losses.append(float(m["loss"]))
        if client is not None:
            client.report_global_step(step + 1)
    print(f"glm prefix-LM: loss {losses[0]:.4f} -> {losses[-1]:.4f} "
          f"(response-only loss, fused prefix mask)")
    if client is not None:
        client.close()


if __name__ == "__main__":
    main()
