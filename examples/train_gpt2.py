"""nanoGPT-style GPT-2 pretraining (BASELINE config #2): the reference's
``auto_accelerate`` DDP path becomes data-parallel pjit here — one
Strategy knob, no wrapper stack.

    # 8 virtual CPU devices, tiny model
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/train_gpt2.py --steps 20

    # GPT-2 124M on the local accelerator
    python examples/train_gpt2.py --preset 124m --steps 50

    # under the elastic launcher (master-backed rendezvous, failover)
    python -m dlrover_tpu.trainer.run --standalone --nnodes 1 \\
        examples/train_gpt2.py --steps 20

Role parity: ``dlrover/examples``' torchrun GPT training scripts driven
through ``auto_accelerate`` with the DDP/parallel-mode optimization.
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.models import gpt2
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.trainer.conf import build_configuration
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.executor import TrainExecutor


def synthetic_batches(vocab_size, batch, seq, seed=0):
    rng = np.random.RandomState(seed)

    def gen():
        while True:
            ids = rng.randint(0, vocab_size, size=(batch, seq + 1))
            yield {
                "input_ids": jnp.asarray(ids[:, :-1]),
                "labels": jnp.asarray(ids[:, 1:]),
            }

    return gen


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="tiny", choices=["tiny", "124m"])
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=0, help="0 = preset default")
    p.add_argument("--ckpt_dir", default="")
    args = p.parse_args()

    if args.preset == "tiny":
        config = gpt2.gpt2_tiny()
        seq = args.seq or 64
    else:
        config = gpt2.gpt2_124m()
        seq = args.seq or min(config.max_seq_len, 1024)

    # pure data parallelism — the nanoGPT/DDP shape; the grad psum is
    # the only collective XLA inserts
    strategy = Strategy(mesh=MeshPlan(data=-1), rule_set="fsdp")
    batches = synthetic_batches(config.vocab_size, args.batch, seq)
    trainer = ElasticTrainer(
        gpt2.make_init_fn(config),
        gpt2.make_loss_fn(config),
        optax.adamw(6e-4, b1=0.9, b2=0.95, weight_decay=0.1),
        next(batches()),
        strategy=strategy,
        ckpt_dir=args.ckpt_dir,
    )
    executor = TrainExecutor(
        trainer,
        train_iter_fn=batches,
        conf=build_configuration({
            "train_steps": args.steps, "log_every_steps": 10,
        }),
    )
    out = executor.train_and_evaluate()
    n_params = sum(
        x.size for x in jax.tree.leaves(executor.state.params)
    )
    print(f"finished at step {out['step']} "
          f"({n_params / 1e6:.1f}M params, {jax.device_count()} devices)")


if __name__ == "__main__":
    main()
