"""GPT-NeoX on a real text file through the dynamic shard service.

The full LLM text path: a line-indexed corpus, byte-level tokenization,
master-dispatched index shards (fast workers eat more shards, resumed
jobs continue mid-epoch), fixed-shape [B, S] batches into a sharded jax
train step.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/train_neox_text.py --steps 30

    # under the elastic launcher the master comes from the env contract
    python -m dlrover_tpu.trainer.run --standalone --nnodes 1 \\
        examples/train_neox_text.py

Role parity: the reference's file-reader path
(``dlrover/trainer/tensorflow/reader/file_reader.py`` fed by
``ShardingClient``) with the estimator swapped for a pjit training loop.
"""

import argparse
import os
import tempfile

import jax
import optax

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.sharding_client import ShardingClient
from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.diagnosis.hang_detector import touch_heartbeat
from dlrover_tpu.models import gpt_neox
from dlrover_tpu.parallel.accelerate import accelerate
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.trainer.text_reader import (
    ByteTokenizer,
    LineIndexedFile,
    ShardedTextBatches,
)


def default_corpus() -> str:
    """Synthesize a deterministic corpus when none is given."""
    path = os.path.join(tempfile.gettempdir(), "neox_demo_corpus.txt")
    if not os.path.exists(path):
        with open(path, "w") as f:
            for i in range(2048):
                f.write(
                    f"sample {i}: the quick brown fox jumps over dog "
                    f"{i % 17} again and again\n"
                )
    return path


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--text", default="", help="path to a text corpus")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--pack", action="store_true",
                   help="pack documents into dense rows (segment ids + "
                        "segmented flash attention; zero pad FLOPs)")
    args = p.parse_args()

    reader = LineIndexedFile(args.text or default_corpus())
    tok = ByteTokenizer(args.seq)
    cfg = gpt_neox.neox_tiny(vocab_size=tok.vocab_size,
                             max_seq_len=args.seq)

    local_master = None
    addr = os.environ.get(NodeEnv.MASTER_ADDR, "")
    if addr:
        client = MasterClient(addr, node_id=int(
            os.environ.get(NodeEnv.NODE_ID, "0")))
    else:
        from dlrover_tpu.master.local_master import start_local_master

        local_master = start_local_master()
        client = MasterClient(local_master.addr, node_id=0)

    sharding = ShardingClient(
        client, "neox_text", batch_size=args.batch,
        dataset_size=reader.count(), num_epochs=4,
        num_minibatches_per_shard=4, storage_type="text",
    )
    source = ShardedTextBatches(sharding, reader, args.batch,
                                tokenizer=tok, seq_len=args.seq,
                                pack=args.pack)

    it = iter(source)
    first = next(it)
    result = accelerate(
        gpt_neox.make_init_fn(cfg), gpt_neox.make_loss_fn(cfg),
        optax.adam(2e-3), first,
        strategy=Strategy(mesh=MeshPlan(data=-1), rule_set="neox"),
    )
    state = result.init_fn(jax.random.PRNGKey(0))

    losses = []
    batch = first
    for step in range(args.steps):
        state, m = result.train_step(
            state, result.shard_batch(batch), jax.random.PRNGKey(step))
        losses.append(float(m["loss"]))
        touch_heartbeat()  # keeps --relaunch-on-hang usable
        client.report_global_step(step + 1)
        batch = next(it, None)
        if batch is None:
            break
    print(f"{len(losses)} steps over {reader.count()} records: "
          f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")

    client.close()
    if local_master is not None:
        local_master.stop()


if __name__ == "__main__":
    main()
